"""Numpy-oracle tests for the detection op corpus (reference:
python/paddle/vision/ops.py — roi_pool, psroi_pool, deform_conv2d, yolo_loss,
read_file/decode_jpeg; operators/detection/)."""

import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as V


class TestRoiPool:
    def test_matches_naive_numpy(self):
        rng = np.random.RandomState(0)
        feat = rng.standard_normal((1, 3, 8, 8)).astype("float32")
        boxes = np.array([[0.0, 0.0, 7.0, 7.0], [2.0, 2.0, 5.0, 6.0]], "float32")
        out = V.roi_pool(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                         np.array([2]), output_size=2, spatial_scale=1.0)
        out = np.asarray(out._data)
        assert out.shape == (2, 3, 2, 2)

        # naive oracle (reference roi_pool_op kernel semantics)
        def oracle(img, box, oh, ow):
            x1, y1, x2, y2 = [int(round(v)) for v in box]
            rw = max(x2 - x1 + 1, 1)
            rh = max(y2 - y1 + 1, 1)
            res = np.zeros((img.shape[0], oh, ow), "float32")
            for i in range(oh):
                for j in range(ow):
                    hs = int(np.floor(i * rh / oh)) + y1
                    he = int(np.ceil((i + 1) * rh / oh)) + y1
                    ws = int(np.floor(j * rw / ow)) + x1
                    we = int(np.ceil((j + 1) * rw / ow)) + x1
                    hs, he = max(hs, 0), min(he, img.shape[1])
                    ws, we = max(ws, 0), min(we, img.shape[2])
                    if he > hs and we > ws:
                        res[:, i, j] = img[:, hs:he, ws:we].max(axis=(1, 2))
            return res

        for r, box in enumerate(boxes):
            np.testing.assert_allclose(out[r], oracle(feat[0], box, 2, 2),
                                       rtol=1e-5)

    def test_batch_routing_via_boxes_num(self):
        rng = np.random.RandomState(1)
        feat = rng.standard_normal((2, 2, 6, 6)).astype("float32")
        boxes = np.array([[0, 0, 5, 5], [0, 0, 5, 5]], "float32")
        out = V.roi_pool(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                         np.array([1, 1]), output_size=1)
        out = np.asarray(out._data)
        # one roi per image: each output must equal that image's global max
        np.testing.assert_allclose(out[0, :, 0, 0], feat[0].max(axis=(1, 2)), rtol=1e-5)
        np.testing.assert_allclose(out[1, :, 0, 0], feat[1].max(axis=(1, 2)), rtol=1e-5)


class TestPSRoiPool:
    def test_constant_input(self):
        # constant feature map → every bin averages to the constant
        oh = ow = 2
        out_ch = 3
        feat = np.full((1, out_ch * oh * ow, 8, 8), 2.5, "float32")
        boxes = np.array([[0.0, 0.0, 7.0, 7.0]], "float32")
        out = V.psroi_pool(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                           np.array([1]), output_size=(oh, ow))
        out = np.asarray(out._data)
        assert out.shape == (1, out_ch, oh, ow)
        np.testing.assert_allclose(out, 2.5, rtol=1e-6)

    def test_position_sensitivity(self):
        # channel k responds only in its own bin: make channel groups distinct
        oh = ow = 2
        feat = np.zeros((1, oh * ow, 4, 4), "float32")
        for k in range(oh * ow):
            feat[0, k] = k + 1.0
        boxes = np.array([[0.0, 0.0, 3.0, 3.0]], "float32")
        out = V.psroi_pool(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                           np.array([1]), output_size=(oh, ow))
        out = np.asarray(out._data)[0, 0]  # (oh, ow), out_ch=1
        np.testing.assert_allclose(out, [[1.0, 2.0], [3.0, 4.0]], rtol=1e-6)


class TestDeformConv2D:
    def test_zero_offset_equals_conv(self):
        """deform_conv2d with zero offsets reduces to a standard conv."""
        import jax
        from jax import lax

        rng = np.random.RandomState(0)
        x = rng.standard_normal((2, 4, 9, 9)).astype("float32")
        w = (rng.standard_normal((6, 4, 3, 3)) * 0.1).astype("float32")
        off = np.zeros((2, 2 * 9, 7, 7), "float32")
        out = V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                              paddle.to_tensor(w), stride=1, padding=0)
        ref = lax.conv_general_dilated(x, w, (1, 1), "VALID",
                                       dimension_numbers=("NCHW", "OIHW", "NCHW"))
        np.testing.assert_allclose(np.asarray(out._data), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_mask_scales_output(self):
        rng = np.random.RandomState(1)
        x = rng.standard_normal((1, 2, 6, 6)).astype("float32")
        w = (rng.standard_normal((2, 2, 3, 3)) * 0.1).astype("float32")
        off = np.zeros((1, 2 * 9, 4, 4), "float32")
        half = np.full((1, 9, 4, 4), 0.5, "float32")
        full = np.ones((1, 9, 4, 4), "float32")
        o_half = np.asarray(V.deform_conv2d(
            paddle.to_tensor(x), paddle.to_tensor(off), paddle.to_tensor(w),
            padding=0, mask=paddle.to_tensor(half))._data)
        o_full = np.asarray(V.deform_conv2d(
            paddle.to_tensor(x), paddle.to_tensor(off), paddle.to_tensor(w),
            padding=0, mask=paddle.to_tensor(full))._data)
        np.testing.assert_allclose(o_half, 0.5 * o_full, rtol=1e-5, atol=1e-6)

    def test_integer_offset_shifts_sampling(self):
        # shifting every sample by exactly one pixel right == conv on shifted input
        rng = np.random.RandomState(2)
        x = rng.standard_normal((1, 1, 8, 8)).astype("float32")
        w = np.ones((1, 1, 1, 1), "float32")
        # K=1 kernel: offset (dy=0, dx=1) at every output position
        off = np.zeros((1, 2, 8, 8), "float32")
        off[:, 1] = 1.0
        out = np.asarray(V.deform_conv2d(
            paddle.to_tensor(x), paddle.to_tensor(off), paddle.to_tensor(w),
            stride=1, padding=0)._data)
        np.testing.assert_allclose(out[0, 0, :, :-1], x[0, 0, :, 1:],
                                   rtol=1e-5, atol=1e-6)
        # out-of-bounds rightmost column samples zero
        np.testing.assert_allclose(out[0, 0, :, -1], 0.0, atol=1e-6)


class TestYoloLoss:
    def _inputs(self, N=2, H=4, W=4, cls=3, B=2, seed=0):
        rng = np.random.RandomState(seed)
        S = 3
        x = (rng.standard_normal((N, S * (5 + cls), H, W)) * 0.1).astype("float32")
        gt_box = np.zeros((N, B, 4), "float32")
        gt_box[:, 0] = [0.5, 0.5, 0.3, 0.4]  # one valid gt per image
        gt_label = np.zeros((N, B), "int32")
        anchors = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119]
        anchor_mask = [0, 1, 2]
        return x, gt_box, gt_label, anchors, anchor_mask, cls

    def test_finite_and_positive(self):
        x, gtb, gtl, anchors, mask, cls = self._inputs()
        loss = V.yolo_loss(paddle.to_tensor(x), paddle.to_tensor(gtb),
                           paddle.to_tensor(gtl), anchors, mask, cls,
                           ignore_thresh=0.7, downsample_ratio=32)
        lv = np.asarray(loss._data)
        assert lv.shape == (2,)
        assert np.all(np.isfinite(lv)) and np.all(lv > 0)

    def test_no_gt_only_objectness(self):
        x, gtb, gtl, anchors, mask, cls = self._inputs()
        gtb[:] = 0.0  # no valid gts
        loss = np.asarray(V.yolo_loss(
            paddle.to_tensor(x), paddle.to_tensor(gtb), paddle.to_tensor(gtl),
            anchors, mask, cls, ignore_thresh=0.7, downsample_ratio=32)._data)
        # pure-negative objectness BCE of small logits ≈ S*H*W*log(2) each
        approx = 3 * 4 * 4 * np.log(2.0)
        assert np.all(np.abs(loss - approx) < 0.2 * approx)

    def test_gradient_flows(self):
        import jax
        import jax.numpy as jnp
        x, gtb, gtl, anchors, mask, cls = self._inputs()

        def f(xx):
            out = V.yolo_loss(xx, jnp.asarray(gtb), jnp.asarray(gtl),
                              anchors, mask, cls, 0.7, 32)
            return jnp.sum(out)

        g = jax.grad(f)(jnp.asarray(x))
        assert np.all(np.isfinite(np.asarray(g)))
        assert float(jnp.abs(g).sum()) > 0


class TestReadDecode:
    def test_jpeg_roundtrip(self):
        from PIL import Image

        # smooth gradient — random noise is exactly what JPEG throws away
        gy, gx = np.mgrid[0:16, 0:16]
        img = np.stack([gy * 16, gx * 16, (gy + gx) * 8], -1).astype("uint8")
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "x.jpg")
            Image.fromarray(img).save(path, quality=95)
            raw = V.read_file(path)
            assert np.asarray(raw._data).dtype == np.uint8
            dec = V.decode_jpeg(raw)
        arr = np.asarray(dec._data)
        assert arr.shape == (3, 16, 16)
        # lossy codec: just require rough agreement
        assert np.mean(np.abs(arr.astype("int32").transpose(1, 2, 0)
                              - img.astype("int32"))) < 20


class TestYoloLossGtScore:
    def test_soft_score_changes_objectness_target(self):
        import jax.numpy as jnp
        rng = np.random.RandomState(3)
        S, cls, H, W = 3, 2, 4, 4
        x = (rng.standard_normal((1, S * (5 + cls), H, W)) * 0.1).astype("float32")
        gtb = np.zeros((1, 1, 4), "float32")
        gtb[0, 0] = [0.5, 0.5, 0.3, 0.4]
        gtl = np.zeros((1, 1), "int32")
        anchors = [10, 13, 16, 30, 33, 23]
        kw = dict(anchors=anchors, anchor_mask=[0, 1, 2], class_num=cls,
                  ignore_thresh=0.7, downsample_ratio=32)
        full = V.yolo_loss(paddle.to_tensor(x), paddle.to_tensor(gtb),
                           paddle.to_tensor(gtl),
                           gt_score=paddle.to_tensor(np.ones((1, 1), "float32")),
                           **kw)
        soft = V.yolo_loss(paddle.to_tensor(x), paddle.to_tensor(gtb),
                           paddle.to_tensor(gtl),
                           gt_score=paddle.to_tensor(np.full((1, 1), 0.5, "float32")),
                           **kw)
        none = V.yolo_loss(paddle.to_tensor(x), paddle.to_tensor(gtb),
                           paddle.to_tensor(gtl), **kw)
        # score=1 must equal the no-score path; score=0.5 must differ
        np.testing.assert_allclose(np.asarray(full._data), np.asarray(none._data),
                                   rtol=1e-6)
        assert not np.allclose(np.asarray(soft._data), np.asarray(full._data))


class TestNewTransforms:
    def test_saturation_hue_rotation(self):
        import paddle_tpu.vision.transforms as T
        rng = np.random.RandomState(0)
        img = (rng.rand(16, 16, 3) * 255).astype("uint8")
        assert T.SaturationTransform(0.4)(img).shape == (16, 16, 3)
        assert T.HueTransform(0.2)(img).shape == (16, 16, 3)
        assert T.RandomRotation(30)(img).shape == (16, 16, 3)
        # zero-strength transforms are identity (within fp rounding)
        f32 = img.astype("float32")
        np.testing.assert_allclose(T.HueTransform(0.0)(f32), f32, atol=1e-3)
        np.testing.assert_allclose(T.SaturationTransform(0.0)(f32), f32,
                                   atol=1e-3)
        np.testing.assert_allclose(T.RandomRotation(0)(f32), f32, atol=1e-3)

    def test_grayscale_saturation_zero_matches_grayscale(self):
        import paddle_tpu.vision.transforms as T
        rng = np.random.RandomState(1)
        img = rng.rand(8, 8, 3).astype("float32")

        class Fixed(T.SaturationTransform):
            def _apply_image(self, im):
                gray = (im[..., :3] @ np.asarray([0.299, 0.587, 0.114],
                                                 "float32"))[..., None]
                return np.broadcast_to(gray, im.shape)
        out = Fixed(0.0)(img)
        assert np.allclose(out[..., 0], out[..., 1])
