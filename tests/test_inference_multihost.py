"""Inference facade parity + multi-host bootstrap (VERDICT round-1 #10).

- export→predict parity: jit.save artifact served through the
  Config/Predictor API must reproduce the eager forward bitwise.
- multi-host: a real 2-process jax.distributed rendezvous through the
  PADDLE_* env contract (reference test_dist_base.py:783 runs the same
  2-worker gate with NCCL; here the coordinator is jax's distributed
  service on localhost and the collective runs over the CPU backend).
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle


class TestInferenceFacade:
    def _export(self, tmp_path):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        path = str(tmp_path / "m" / "model")
        spec = [paddle.jit.InputSpec(shape=[2, 8], dtype="float32",
                                     name="feats")]
        paddle.jit.save(model, path, input_spec=spec)
        return model, path

    def test_export_predict_parity(self, tmp_path):
        model, path = self._export(tmp_path)
        x = np.random.RandomState(0).standard_normal((2, 8)).astype(np.float32)
        ref = np.asarray(model(paddle.to_tensor(x))._data)

        config = paddle.inference.Config(path)
        predictor = paddle.inference.create_predictor(config)
        names = predictor.get_input_names()
        assert names == ["feats"]
        predictor.get_input_handle("feats").copy_from_cpu(x)
        predictor.run()
        out_names = predictor.get_output_names()
        out = predictor.get_output_handle(out_names[0]).copy_to_cpu()
        np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)

    def test_positional_run_and_clone(self, tmp_path):
        model, path = self._export(tmp_path)
        x = np.random.RandomState(1).standard_normal((2, 8)).astype(np.float32)
        ref = np.asarray(model(paddle.to_tensor(x))._data)
        predictor = paddle.inference.create_predictor(
            paddle.inference.Config(path))
        outs = predictor.run([x])
        np.testing.assert_allclose(outs[0], ref, rtol=1e-6, atol=1e-6)
        clone = predictor.clone()
        assert clone._layer is predictor._layer  # shares executable+weights
        outs2 = clone.run([x])
        np.testing.assert_allclose(outs2[0], ref, rtol=1e-6, atol=1e-6)

    def test_missing_model_raises(self, tmp_path):
        with pytest.raises(ValueError, match="not found"):
            paddle.inference.create_predictor(
                paddle.inference.Config(str(tmp_path / "nope")))


_WORKER = textwrap.dedent("""
    import os, sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu.distributed import env as dist_env

    dist_env.init_parallel_env()
    assert jax.process_count() == 2, jax.process_count()
    rank = jax.process_index()
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = jax.devices()           # both processes' cpu devices
    mesh = Mesh(np.array(devs), ("data",))
    # each process contributes its rank+1; global psum must see 1+2=3 per
    # device pair scaling — use make_array_from_callback so each host only
    # provides its own shard
    def cb(idx):
        return np.full((1,), float(jax.process_index() + 1), np.float32)
    arr = jax.make_array_from_callback(
        (len(devs),), NamedSharding(mesh, P("data")), lambda idx: np.full(
            (1,), float(rank + 1), np.float32))
    total = jax.jit(lambda a: jnp.sum(a),
                    out_shardings=NamedSharding(mesh, P()))(arr)
    print("RESULT", rank, float(np.asarray(total)), flush=True)
""")


@pytest.mark.skipif(os.environ.get("PADDLE_TPU_SKIP_MULTIHOST") == "1",
                    reason="multihost disabled")
def test_two_process_bootstrap(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_MASTER": f"127.0.0.1:{port}",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER], env=env, cwd="/root/repo",
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-host worker timed out")
        assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
        outs.append(out)
    for rank, out in enumerate(outs):
        lines = [ln for ln in out.splitlines() if ln.startswith("RESULT")]
        assert lines, f"no result from rank {rank}: {out}"
        _, r, total = lines[0].split()
        assert int(r) == rank
        # sum over 2 process-shards holding 1.0 and 2.0
        assert float(total) == pytest.approx(3.0)
