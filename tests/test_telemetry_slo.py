"""SLO engine (paddle_tpu/telemetry_slo.py, ISSUE 10): mergeable
percentile sketch accuracy, windowed stores, and the multi-window
burn-rate alert lifecycle — pending → firing → resolved — under a
DETERMINISTIC fake clock (no sleeps anywhere), including the
no-flapping-at-the-boundary hysteresis contract.

No reference counterpart: this is the SRE alerting layer over the
reference's monitor.h counters."""

import numpy as np
import pytest

from paddle_tpu.telemetry import Tracer
from paddle_tpu.telemetry_slo import Objective, PercentileSketch, SLOMonitor
from paddle_tpu.utils.stats import prom_escape_label, prom_sample


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------- sketch --

class TestPercentileSketch:
    def test_quantile_within_alpha(self):
        rng = np.random.RandomState(0)
        vals = rng.lognormal(size=5000)
        sk = PercentileSketch(alpha=0.02)
        for v in vals:
            sk.add(float(v))
        for q in (0.5, 0.9, 0.99):
            exact = float(np.quantile(vals, q))
            got = sk.quantile(q)
            assert abs(got - exact) / exact < 0.05, (q, got, exact)
        assert sk.n == 5000
        assert sk.min == pytest.approx(float(vals.min()))
        assert sk.max == pytest.approx(float(vals.max()))

    def test_merge_equals_union(self):
        rng = np.random.RandomState(1)
        vals = rng.exponential(size=2000)
        whole = PercentileSketch()
        a, b = PercentileSketch(), PercentileSketch()
        for i, v in enumerate(vals):
            whole.add(float(v))
            (a if i % 2 else b).add(float(v))
        a.merge(b)
        assert a.n == whole.n
        for q in (0.5, 0.95, 0.99):
            assert a.quantile(q) == whole.quantile(q)
        assert a.count_above(1.0) == whole.count_above(1.0)

    def test_merge_rejects_mismatched_alpha(self):
        with pytest.raises(ValueError, match="alpha"):
            PercentileSketch(0.02).merge(PercentileSketch(0.05))

    def test_count_above_and_zero_bucket(self):
        sk = PercentileSketch()
        for v in (0.0, 0.0, 1.0, 10.0, 100.0):
            sk.add(v)
        assert sk.count_above(5.0) == 2          # 10, 100
        assert sk.count_above(-1.0) == 5         # everything
        assert sk.quantile(0.0) == 0.0           # zero bucket
        assert sk.n == 5

    def test_empty(self):
        sk = PercentileSketch()
        assert sk.quantile(0.5) is None
        assert sk.count_above(1.0) == 0
        assert sk.snapshot()["n"] == 0


# --------------------------------------------------------------- windows --

class TestWindowedStores:
    def test_samples_age_out_of_window(self):
        clk = FakeClock()
        slo = SLOMonitor(clock=clk, resolution_s=1.0)
        obj = slo.add_objective(Objective.latency(
            "ttft", "ttft_s", 0.1, windows=(10.0,)))
        for _ in range(5):
            slo.observe("ttft_s", 1.0)           # all bad
            clk.advance(1.0)
        assert slo.burn_rates(obj)["10"] > 0
        clk.advance(30.0)                        # everything ages out
        assert slo.burn_rates(obj)["10"] == 0.0

    def test_counter_window_sums(self):
        clk = FakeClock()
        slo = SLOMonitor(clock=clk, resolution_s=1.0)
        obj = slo.add_objective(Objective.ratio(
            "shed", bad="shed", total="submitted", target=0.1,
            windows=(10.0,)))
        for _ in range(10):
            slo.count("submitted")
            clk.advance(1.0)
        slo.count("shed", 5)
        # 5/10 shed over 10% budget -> burn 5
        assert slo.burn_rates(obj)["10"] == pytest.approx(5.0)
        clk.advance(60.0)
        assert slo.burn_rates(obj)["10"] == 0.0

    def test_bounded_buckets(self):
        clk = FakeClock()
        slo = SLOMonitor(clock=clk, resolution_s=1.0, horizon_s=10.0)
        for _ in range(1000):
            slo.observe("m", 1.0)
            clk.advance(1.0)
        assert len(slo._samples["m"].buckets) <= 13   # horizon-bounded


# ------------------------------------------------------------- lifecycle --

def _ttft_monitor(clk, tracer=None, **kw):
    kw.setdefault("windows", (60.0, 10.0))
    kw.setdefault("burn_threshold", 2.0)
    kw.setdefault("for_s", 5.0)
    kw.setdefault("clear_s", 10.0)
    slo = SLOMonitor(clock=clk, tracer=tracer, resolution_s=1.0)
    obj = slo.add_objective(Objective.latency(
        "ttft_p99", "ttft_s", target_s=0.1, compliance=0.99, **kw))
    return slo, obj


class TestBurnRateLifecycle:
    def test_pending_firing_resolved_on_regression_and_recovery(self):
        """The acceptance lifecycle: a synthetic TTFT regression drives
        pending → firing; recovery drives resolved — and every
        transition lands in the snapshot, the prometheus export, AND the
        tracer ring."""
        clk = FakeClock()
        tracer = Tracer()
        slo, obj = _ttft_monitor(clk, tracer)
        # healthy traffic: burn 0, no alert
        for _ in range(30):
            slo.observe("ttft_s", 0.01)
            clk.advance(1.0)
            slo.evaluate()
        assert slo.evaluate()[0]["state"] == "inactive"
        # regression: every sample breaches -> burn = 1/budget = 100
        fired_at = None
        for _ in range(20):
            slo.observe("ttft_s", 0.5)
            clk.advance(1.0)
            row = slo.evaluate()[0]
            if row["state"] == "firing" and fired_at is None:
                fired_at = clk.t
        assert fired_at is not None
        row = slo.evaluate()[0]
        assert row["state"] == "firing"
        assert all(b >= obj.burn_threshold
                   for b in row["burn_rates"].values())
        # recovery: good samples push burn under the resolve band on the
        # short window quickly, on the long window later
        for _ in range(90):
            slo.observe("ttft_s", 0.01)
            clk.advance(1.0)
            slo.evaluate()
        assert slo.evaluate()[0]["state"] == "inactive"
        whats = [t["what"] for t in slo.snapshot()["transitions"]]
        assert whats == ["pending", "firing", "resolved"]
        # transitions rode the tracer ring as slo events
        assert [e["what"] for e in tracer.events("slo")] == whats
        assert all(e["objective"] == "ttft_p99"
                   for e in tracer.events("slo"))
        # ring events carry the TRACER's timebase (seconds since its
        # t0), not the monitor's absolute clock: a wedged loop whose
        # newest event is an slo transition must still age out on
        # /healthz.  The monitor-clock reading rides along as "at".
        for e in tracer.events("slo"):
            assert 0.0 <= e["ts"] <= tracer.now()
            assert e["at"] >= 3.0          # the fake clock, well past t0
        assert tracer.last_event_age_s() < 60.0
        # and the exports agree
        text = slo.prometheus_text()
        assert 'paddle_tpu_slo_alert_state{objective="ttft_p99"} 0' in text
        assert "paddle_tpu_slo_alerts_firing 1" in text
        assert "paddle_tpu_slo_alerts_resolved 1" in text

    def test_pending_needs_for_s_before_firing(self):
        clk = FakeClock()
        slo, obj = _ttft_monitor(clk, for_s=8.0)
        for _ in range(3):
            slo.observe("ttft_s", 0.5)
            clk.advance(1.0)
        assert slo.evaluate()[0]["state"] == "pending"
        for _ in range(10):
            slo.observe("ttft_s", 0.5)
            clk.advance(1.0)
            slo.evaluate()
        assert slo.evaluate()[0]["state"] == "firing"

    def test_short_blip_cancels_without_firing(self):
        """A blip shorter than for_s never fires: pending → cancelled,
        and no firing/resolved transitions exist."""
        clk = FakeClock()
        slo, obj = _ttft_monitor(clk, for_s=30.0)
        for _ in range(3):
            slo.observe("ttft_s", 0.5)
            clk.advance(1.0)
            slo.evaluate()
        assert slo.evaluate()[0]["state"] == "pending"
        for _ in range(30):
            slo.observe("ttft_s", 0.01)
            clk.advance(1.0)
            slo.evaluate()
        whats = [t["what"] for t in slo.snapshot()["transitions"]]
        assert whats == ["pending", "cancelled"]

    def test_no_flapping_at_the_boundary(self):
        """An SLI hovering AT the burn threshold must not flap: once
        firing, the alert stays firing until burn drops clearly below
        the resolve band (resolve_ratio hysteresis) for clear_s."""
        clk = FakeClock()
        slo, obj = _ttft_monitor(clk, burn_threshold=2.0,
                                 resolve_ratio=0.9)
        budget = obj.budget                      # 0.01
        # drive to firing
        for _ in range(20):
            slo.observe("ttft_s", 0.5)
            clk.advance(1.0)
            slo.evaluate()
        assert slo.evaluate()[0]["state"] == "firing"
        # hover exactly at the boundary: bad fraction ~= 2x budget
        # (burn ~2.0), oscillating slightly above/below the threshold
        # but never below resolve_ratio * threshold
        rng = np.random.RandomState(0)
        for i in range(120):
            bad = budget * (2.0 + (0.3 if i % 2 else -0.05))
            for _ in range(40):
                slo.observe("ttft_s",
                            0.5 if rng.rand() < bad else 0.01)
            clk.advance(1.0)
            slo.evaluate()
        whats = [t["what"] for t in slo.snapshot()["transitions"]]
        assert whats == ["pending", "firing"], whats   # never resolved
        assert slo.evaluate()[0]["state"] == "firing"

    def test_multi_window_and_gate(self):
        """A stale long-window breach with a recovered short window does
        NOT alert (the multi-window AND): the incident is over."""
        clk = FakeClock()
        slo, obj = _ttft_monitor(clk, for_s=0.0)
        for _ in range(20):
            slo.observe("ttft_s", 0.5)
            clk.advance(1.0)
        # 20s of pure recovery: the 10s window is clean, the 60s window
        # still remembers the damage
        for _ in range(20):
            slo.observe("ttft_s", 0.01)
            clk.advance(1.0)
        row = slo.evaluate()[0]
        assert row["burn_rates"]["60"] >= obj.burn_threshold
        assert row["burn_rates"]["10"] < obj.burn_threshold
        assert row["state"] in ("inactive", "pending")
        assert not [t for t in slo.snapshot()["transitions"]
                    if t["what"] == "firing"]


# --------------------------------------------------- objectives / feeds --

class TestObjectivesAndFeeds:
    def test_ratio_objective_shed_rate(self):
        clk = FakeClock()
        tracer = Tracer()
        slo = SLOMonitor(clock=clk, tracer=tracer, resolution_s=1.0)
        slo.add_objective(Objective.ratio(
            "shed_rate", bad="shed", total="submitted", target=0.05,
            windows=(30.0, 10.0), burn_threshold=2.0, for_s=0.0))
        for _ in range(10):
            slo.count("submitted", 10)
            clk.advance(1.0)
            slo.evaluate()
        assert slo.evaluate()[0]["state"] == "inactive"
        for _ in range(10):
            slo.count("submitted", 10)
            slo.count("shed", 3)                 # 30% shed vs 5% target
            clk.advance(1.0)
            slo.evaluate()
        row = slo.evaluate()[0]
        assert row["state"] == "firing"
        assert row["sli"]["rate"] > 0.05

    def test_goodput_floor_via_ledger_pull(self):
        class StubLedger:
            goodput = 0.9

            def snapshot(self):
                return {"goodput": self.goodput}

        clk = FakeClock()
        slo = SLOMonitor(clock=clk, resolution_s=1.0)
        led = StubLedger()
        slo.attach_ledger(led)
        slo.add_objective(Objective.floor(
            "goodput", "goodput", floor=0.5, compliance=0.9,
            windows=(30.0, 10.0), burn_threshold=1.5, for_s=0.0))
        for _ in range(15):
            clk.advance(1.0)
            slo.evaluate()                       # pulls 0.9 each time
        assert slo.evaluate()[0]["state"] == "inactive"
        led.goodput = 0.2                        # collapse below floor
        for _ in range(15):
            clk.advance(1.0)
            slo.evaluate()
        assert slo.evaluate()[0]["state"] == "firing"

    def test_tracer_forwarding_feeds_samples_and_counts(self):
        """Tracer.set_slo: retired requests feed ttft_s samples and
        terminal counts with NO extra instrumentation."""
        clk = FakeClock()
        slo = SLOMonitor(clock=clk, resolution_s=1.0)
        tr = Tracer()
        tr.set_slo(slo)
        tr.request_event(1, "queued", prompt_len=3)
        tr.request_event(1, "first_token")
        tr.request_event(1, "token")
        tr.request_event(1, "token")
        tr.request_event(1, "retired")
        assert slo._window_sketch("ttft_s", 60.0, clk.t).n == 1
        assert slo._window_sketch("itl_s", 60.0, clk.t).n == 1
        assert slo._window_count("requests_retired", 60.0, clk.t) == 1

    def test_objective_validation(self):
        with pytest.raises(ValueError, match="kind"):
            Objective("x", "nope", 1.0)
        with pytest.raises(ValueError, match="sample metric"):
            Objective("x", "latency", 1.0)
        with pytest.raises(ValueError, match="counter names"):
            Objective("x", "ratio", 0.1, bad="b")
        with pytest.raises(ValueError, match="compliance"):
            Objective.latency("x", "m", 1.0, compliance=1.0)
        slo = SLOMonitor(clock=FakeClock())
        slo.add_objective(Objective.latency("dup", "m", 1.0))
        with pytest.raises(ValueError, match="already defined"):
            slo.add_objective(Objective.latency("dup", "m", 1.0))

    def test_empty_window_is_no_evidence(self):
        clk = FakeClock()
        slo, obj = _ttft_monitor(clk)
        for _ in range(30):
            clk.advance(1.0)
            assert slo.evaluate()[0]["state"] == "inactive"
        assert slo.snapshot()["transitions"] == []


# --------------------------------------------------------------- exports --

class TestExports:
    def test_snapshot_shape(self):
        clk = FakeClock()
        slo, obj = _ttft_monitor(clk)
        slo.observe("ttft_s", 0.05)
        snap = slo.snapshot()
        assert snap["objectives"][0]["name"] == "ttft_p99"
        assert snap["objectives"][0]["budget"] == pytest.approx(0.01)
        row = snap["status"][0]
        assert set(row["burn_rates"]) == {"60", "10"}
        assert row["sli"]["n"] == 1
        assert snap["alerts_firing"] == 0
        import json
        json.dumps(snap)                         # JSON-able end to end

    def test_prometheus_label_escaping_via_shared_helper(self):
        r"""Objective names with quotes/backslashes/newlines render
        escaped — through utils.stats.prom_escape_label, the ONE shared
        escaping implementation (the consolidation satellite)."""
        assert prom_escape_label('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
        assert prom_sample("m", 1, {"k": 'v"w'}) == 'm{k="v\\"w"} 1'
        clk = FakeClock()
        slo = SLOMonitor(clock=clk, resolution_s=1.0)
        slo.add_objective(Objective.latency('odd"name\\x', "m", 1.0))
        text = slo.prometheus_text()
        assert 'objective="odd\\"name\\\\x"' in text

    def test_ops_server_slo_route_and_metrics(self):
        import json as _json
        import urllib.request
        from paddle_tpu.ops_server import OpsServer
        clk = FakeClock()
        slo, obj = _ttft_monitor(clk)
        slo.observe("ttft_s", 0.01)
        srv = OpsServer()
        srv.attach(slo)
        url = srv.start()
        try:
            payload = _json.loads(urllib.request.urlopen(
                url + "/slo", timeout=10).read())
            assert payload["objectives"][0]["name"] == "ttft_p99"
            text = urllib.request.urlopen(
                url + "/metrics", timeout=10).read().decode()
            assert "paddle_tpu_slo_burn_rate" in text
        finally:
            srv.stop()

    def test_ops_server_slo_404_when_absent(self):
        import urllib.error
        import urllib.request
        from paddle_tpu.ops_server import OpsServer
        srv = OpsServer()
        url = srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url + "/slo", timeout=10)
            assert ei.value.code == 404
        finally:
            srv.stop()
