"""Ragged mixed prefill+decode serving engine
(paddle_tpu/serving_paged.py: RaggedPagedContinuousBatchingEngine): ONE
compiled program per scheduler tick serves any mixture of admission
prefill chunks and in-flight decode rows — no per-bucket prefill program
family, no separate decode tick — while every request's tokens stay
oracle-exact vs solo model.generate(), across fp32 and int8 KV pools,
prefix-cache hits, preemption, and per-request sampling planes.

No reference counterpart (the reference serves static batches only); the
oracle is the framework's own single-request generation path."""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.flags import set_flags
from paddle_tpu.models.gpt import GPTConfig, GPTModel
from paddle_tpu.serving import RaggedPagedContinuousBatchingEngine


@pytest.fixture(scope="module")
def model_and_params():
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=96,
                    compute_dtype="float32")
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    return model, params


def _solo_greedy(model, params, prompt, n, **kw):
    out = model.generate(params, jnp.asarray([prompt], jnp.int32), n,
                         greedy=True, **kw)
    return [int(t) for t in np.asarray(out)[0]]


PROMPTS = [[5, 17, 3], [40, 2], [9, 9, 9, 9, 9, 1], [61], [8, 30, 12, 4],
           [77, 13, 2, 5, 6, 7, 8]]


class TestRaggedParity:
    def test_interleaved_matches_solo_generate(self, model_and_params):
        """Six ragged requests through 3 slots with retirement and
        re-admission: token-for-token solo parity, clean allocator."""
        model, params = model_and_params
        budgets = [10, 4, 7, 12, 3, 8]
        eng = RaggedPagedContinuousBatchingEngine(
            model, params, max_slots=3, max_len=32, block_size=4,
            prompt_buckets=[8, 16], token_budget=12)
        rids = [eng.add_request(p, n) for p, n in zip(PROMPTS, budgets)]
        got = eng.run_to_completion(max_ticks=300)
        assert sorted(got) == sorted(rids)
        for rid, p, n in zip(rids, PROMPTS, budgets):
            assert got[rid] == _solo_greedy(model, params, p, n), \
                f"request {rid} diverged"
        assert eng.blocks_in_use == 0

    def test_one_program_serves_the_mixed_tick(self, model_and_params):
        """THE tentpole claim: a workload mixing admissions into running
        decode dispatches ONLY ragged_step programs — no per-bucket
        prefill family, no cached-prefill family, no separate decode
        programs — and at least one step really carried prefill AND
        decode rows.  Program count stays bounded by table-width buckets
        (and a fresh engine adds none)."""
        model, params = model_and_params
        model.__dict__.pop("_serving_programs", None)

        def make():
            return RaggedPagedContinuousBatchingEngine(
                model, params, max_slots=3, max_len=32, block_size=4,
                prompt_buckets=[8, 16], token_budget=12)

        eng = make()
        r0 = eng.add_request(PROMPTS[0], 8)
        eng.step()                               # r0 prefills + first token
        r1 = eng.add_request(PROMPTS[5], 6)      # arrives mid-decode
        r2 = eng.add_request(PROMPTS[1], 5)
        got = eng.run_to_completion(max_ticks=200)
        kinds = {k[0] for k in model._serving_programs}
        assert kinds == {"ragged_step"}, kinds
        assert eng.mixed_steps >= 1
        n_progs = len(model._serving_programs)
        eng2 = make()                            # same shapes: no new progs
        eng2.add_request(PROMPTS[2], 5)
        eng2.run_to_completion(max_ticks=200)
        assert len(model._serving_programs) == n_progs
        for rid, p, n in [(r0, PROMPTS[0], 8), (r1, PROMPTS[5], 6),
                          (r2, PROMPTS[1], 5)]:
            assert got[rid] == _solo_greedy(model, params, p, n)

    def test_prompt_longer_than_budget_spans_steps(self, model_and_params):
        """A bucket-16 prompt under a budget of 6 rows prefills across
        several ragged steps (chunking is inherent — no prefill_chunk
        knob) while a short request decodes next to it."""
        model, params = model_and_params
        eng = RaggedPagedContinuousBatchingEngine(
            model, params, max_slots=2, max_len=48, block_size=4,
            prompt_buckets=[4, 16], token_budget=6)
        r0 = eng.add_request([40, 2], 12)              # bucket 4
        long_p = list(range(3, 17))                    # bucket 16 > budget
        r1 = eng.add_request(long_p, 5)
        got = eng.run_to_completion(max_ticks=300)
        assert got[r0] == _solo_greedy(model, params, [40, 2], 12)
        assert got[r1] == _solo_greedy(model, params, long_p, 5)
        assert eng.mixed_steps >= 1

    @pytest.mark.parametrize("interp", [
        False,
        pytest.param(True, marks=pytest.mark.slow),  # interpret-mode
        # Pallas is minutes-scale on CPU; the quick tier keeps the
        # cheaper kernel_on_off interpret coverage
    ])
    def test_int8_kv_pool(self, interp):
        """int8 (values, scales) pools ride the ragged step with dequant
        fused into the kernel (interpret arm) or the gather fallback:
        parity vs solo generate on the SAME int8-cached model."""
        paddle.seed(11)
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=96,
                        compute_dtype="float32", kv_cache_dtype="int8")
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        set_flags({"FLAGS_paged_attn_interpret": interp})
        try:
            eng = RaggedPagedContinuousBatchingEngine(
                model, params, max_slots=2, max_len=32, block_size=8,
                prompt_buckets=[8], token_budget=10)
            budgets = [9, 5, 7]
            rids = [eng.add_request(p, n)
                    for p, n in zip(PROMPTS[:3], budgets)]
            got = eng.run_to_completion(max_ticks=200)
        finally:
            set_flags({"FLAGS_paged_attn_interpret": False})
        for rid, p, n in zip(rids, PROMPTS[:3], budgets):
            assert got[rid] == _solo_greedy(model, params, p, n), \
                f"int8 request {rid} diverged (interp={interp})"

    def test_kernel_on_off_identical(self, model_and_params):
        """Engine outputs are token-identical with the ragged Pallas
        kernel (interpret mode) vs the XLA gather fallback."""
        model, params = model_and_params

        def run(interp):
            set_flags({"FLAGS_paged_attn_interpret": interp})
            try:
                model.__dict__.pop("_serving_programs", None)
                eng = RaggedPagedContinuousBatchingEngine(
                    model, params, max_slots=3, max_len=32, block_size=4,
                    prompt_buckets=[8, 16], token_budget=12)
                rids = [eng.add_request(p, n)
                        for p, n in zip(PROMPTS[:4], [9, 5, 7, 6])]
                got = eng.run_to_completion(max_ticks=200)
                return [got[r] for r in rids]
            finally:
                set_flags({"FLAGS_paged_attn_interpret": False})
                model.__dict__.pop("_serving_programs", None)

        assert run(True) == run(False)


class TestRaggedAllocator:
    @pytest.mark.parametrize("interp", [
        False,
        pytest.param(True, marks=pytest.mark.slow),  # interpret-mode
        # Pallas is minutes-scale on CPU; the quick tier keeps the
        # cheaper kernel_on_off interpret coverage
    ])
    def test_preemption_stays_exact_and_signals_replay(self, interp,
                                                       model_and_params):
        """Two long requests over a pool that fits one: the younger is
        preempted and rerun; outputs stay greedy-exact (kernel interpret
        arm included) and the streaming consumer receives the documented
        on_token(rid, None, False) replay signal before the re-delivered
        prefix."""
        model, params = model_and_params
        events = []
        set_flags({"FLAGS_paged_attn_interpret": interp})
        try:
            eng = RaggedPagedContinuousBatchingEngine(
                model, params, max_slots=2, max_len=32, block_size=4,
                num_blocks=8, prompt_buckets=[8], token_budget=10)
            r0 = eng.add_request(PROMPTS[0], 14)
            r1 = eng.add_request(PROMPTS[1], 14,
                                 on_token=lambda rid, tok, done:
                                 events.append((rid, tok, done)))
            got = eng.run_to_completion(max_ticks=500)
        finally:
            set_flags({"FLAGS_paged_attn_interpret": False})
        assert eng.preemptions >= 1
        assert got[r0] == _solo_greedy(model, params, PROMPTS[0], 14)
        assert got[r1] == _solo_greedy(model, params, PROMPTS[1], 14)
        resets = [i for i, (rid, tok, _) in enumerate(events)
                  if tok is None]
        assert resets, "preempted request never got the replay signal"
        # the stream AFTER the last reset is the complete, exact answer
        tail = [tok for rid, tok, _ in events[resets[-1] + 1:]]
        assert tail == got[r1]
        assert eng.blocks_in_use == 0

    @pytest.mark.parametrize("interp", [
        False,
        pytest.param(True, marks=pytest.mark.slow),  # interpret-mode
        # Pallas is minutes-scale on CPU; the quick tier keeps the
        # cheaper kernel_on_off interpret coverage
    ])
    def test_prefix_cache_reuses_blocks(self, interp, model_and_params):
        """Same-pad shared prefix: the second admission pins the cached
        chain and computes only the suffix rows; outputs stay exact on
        both the kernel (interpret) and gather arms."""
        model, params = model_and_params
        set_flags({"FLAGS_paged_attn_interpret": interp})
        try:
            eng = RaggedPagedContinuousBatchingEngine(
                model, params, max_slots=2, max_len=64, block_size=4,
                prompt_buckets=[16], token_budget=20,
                enable_prefix_cache=True)
            sysp = list(range(7, 19))
            p1, p2 = sysp + [1], sysp + [2]  # same length => shared chain
            ra = eng.add_request(p1, 6)
            got = eng.run_to_completion(max_ticks=200)
            rb = eng.add_request(p2, 6)
            got2 = eng.run_to_completion(max_ticks=200)
        finally:
            set_flags({"FLAGS_paged_attn_interpret": False})
        assert eng.prefix_hits >= 1
        assert eng.prefix_blocks_reused >= 1
        assert got[ra] == _solo_greedy(model, params, p1, 6)
        assert got2[rb] == _solo_greedy(model, params, p2, 6)

    def test_per_request_planes(self, model_and_params):
        """Heterogeneous deterministic configs in one ragged batch — the
        per-request data planes ride the single mixed program."""
        model, params = model_and_params
        eng = RaggedPagedContinuousBatchingEngine(
            model, params, max_slots=3, max_len=48, block_size=4,
            prompt_buckets=[8], token_budget=12, per_request_sampling=True)
        probe = _solo_greedy(model, params, PROMPTS[0], 8)
        eos = probe[1]
        cases = [(PROMPTS[0], 8, {}),
                 (PROMPTS[1], 7, dict(repetition_penalty=5.0)),
                 (PROMPTS[0], 8, dict(min_new_tokens=4, eos_token_id=eos))]
        rids = [eng.add_request(p, n, **c) for p, n, c in cases]
        got = eng.run_to_completion(max_ticks=300)
        for rid, (p, n, c) in zip(rids, cases):
            assert got[rid] == _solo_greedy(model, params, p, n, **c), \
                f"request {rid} cfg={c}"

    def test_ctor_validation(self, model_and_params):
        model, params = model_and_params
        with pytest.raises(ValueError, match="token_budget"):
            RaggedPagedContinuousBatchingEngine(
                model, params, max_slots=4, max_len=32, block_size=4,
                token_budget=2)
        with pytest.raises(NotImplementedError, match="ticks_per_sync"):
            RaggedPagedContinuousBatchingEngine(
                model, params, max_slots=2, max_len=32, block_size=4,
                ticks_per_sync=2)
        with pytest.raises(ValueError, match="prefill_chunk"):
            RaggedPagedContinuousBatchingEngine(
                model, params, max_slots=2, max_len=32, block_size=4,
                prefill_chunk=8)


class TestRaggedSpec:
    """Speculative decoding INSIDE the ragged engine (ISSUE 13): the
    draft's K proposals and the target's verification ride the SAME
    flattened pack as plain decode rows and admission prefill chunks —
    one fused compiled program per (token_budget, table-width) bucket,
    outputs equal to plain greedy decode by the models/_decode.py
    greedy_verify contract."""

    @pytest.fixture(scope="class")
    def draft_and_params(self):
        paddle.seed(77)
        dcfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=1,
                         num_attention_heads=4,
                         max_position_embeddings=96,
                         compute_dtype="float32")
        draft = GPTModel(dcfg)
        return draft, {n: p._data for n, p in draft.named_parameters()}

    def test_mixed_spec_nonspec_single_program(self, model_and_params,
                                               draft_and_params):
        """THE tentpole pin: spec and non-spec requests share a tick
        (admission prefill included), and the whole workload dispatches
        ONLY the fused ragged_spec family — one program per
        (token_budget, table-width) bucket, asserted via the PR 2
        compile counters."""
        model, params = model_and_params
        draft, dparams = draft_and_params
        model.__dict__.pop("_serving_programs", None)
        eng = RaggedPagedContinuousBatchingEngine(
            model, params, max_slots=3, max_len=48, block_size=4,
            prompt_buckets=[8, 16], draft_model=draft,
            draft_params=dparams, draft_k=3)
        r0 = eng.add_request(PROMPTS[0], 9)              # speculates
        eng.step()                                       # r0 activates
        r1 = eng.add_request(PROMPTS[5], 6, spec=False)  # plain rows
        r2 = eng.add_request(PROMPTS[1], 5)              # speculates
        got = eng.run_to_completion(max_ticks=300)
        kinds = {k[0] for k in model._serving_programs}
        assert kinds == {"ragged_spec"}, kinds
        # one compiled program per (token_budget, C) bucket, nothing else
        assert eng._compile_misses == len(model._serving_programs)
        assert eng.mixed_steps >= 1 and eng.spec_rounds >= 1
        assert eng.tokens_drafted > 0
        for rid, p, n in [(r0, PROMPTS[0], 9), (r1, PROMPTS[5], 6),
                          (r2, PROMPTS[1], 5)]:
            assert got[rid] == _solo_greedy(model, params, p, n), rid
        assert eng.blocks_in_use == 0

    def test_perfect_draft_rounds_stats_rollback(self, model_and_params):
        """Self-draft: every proposal accepted — minimal round count,
        acceptance_rate exactly 1.0 on the registry-backed stats, spec
        counters in the Prometheus exposition (the gateway /metrics
        merge concatenates it), and the rejected-page rollback leaves a
        clean allocator."""
        model, params = model_and_params
        K, N = 3, 13
        eng = RaggedPagedContinuousBatchingEngine(
            model, params, max_slots=1, max_len=48, block_size=4,
            prompt_buckets=[8], draft_model=model, draft_params=params,
            draft_k=K)
        rid = eng.add_request([5, 17, 3], N)
        got = eng.run_to_completion(max_ticks=100)
        assert got[rid] == _solo_greedy(model, params, [5, 17, 3], N)
        assert eng.spec_rounds == -(-(N - 1) // (K + 1))
        assert eng.rounds == eng.spec_rounds       # legacy-compat alias
        m = eng.metrics()
        assert m["acceptance_rate"] == 1.0
        assert m["tokens_drafted"] == eng.spec_rounds * K
        assert m["tokens_accepted"] == m["tokens_drafted"]
        assert eng.blocks_in_use == 0
        text = eng.prometheus_text()
        assert "tokens_accepted" in text and "acceptance_rate" in text
        assert m["blocks_allocated"] == m["blocks_released"]

    @pytest.mark.parametrize("seed", [0, 1])
    def test_stream_prefix_fuzz_with_cancels(self, model_and_params,
                                             draft_and_params, seed):
        """Prefix-of-oracle parity under chaos: random spec/non-spec
        mixes over tight pools (preemption replays mid-round) with
        random mid-flight cancels — every finished request equals solo
        generate, every cancelled stream is a PREFIX of it (after the
        documented replay reset), and the allocator quiesces clean."""
        model, params = model_and_params
        draft, dparams = draft_and_params
        rng = np.random.RandomState(300 + seed)
        K = int(rng.choice([1, 2, 4]))
        bs = int(rng.choice([2, 4]))
        worst = -(-(16 + 11 + K - 1) // bs)
        nb = int(rng.randint(worst, worst * 2))
        eng = RaggedPagedContinuousBatchingEngine(
            model, params, max_slots=int(rng.randint(1, 4)), max_len=48,
            block_size=bs, num_blocks=nb, prompt_buckets=[8, 16],
            draft_model=draft, draft_params=dparams, draft_k=K)
        streams = {}

        def on_token(rid, tok, done):
            if tok is None and not done:
                streams[rid] = []            # replay reset: discard
            elif tok is not None:
                streams.setdefault(rid, []).append(tok)

        reqs = []
        for _ in range(int(rng.randint(4, 8))):
            p = [int(t) for t in rng.randint(1, 97, rng.randint(1, 15))]
            n = int(rng.randint(1, 12))
            rid = eng.add_request(p, n, on_token=on_token,
                                  spec=bool(rng.rand() < 0.7))
            reqs.append((rid, p, n))
            for _ in range(int(rng.randint(0, 3))):
                eng.step()
            if rng.rand() < 0.3:
                eng.cancel(reqs[int(rng.randint(0, len(reqs)))][0])
        got = eng.run_to_completion(max_ticks=800)
        for rid, p, n in reqs:
            want = _solo_greedy(model, params, p, n)
            stream = streams.get(rid, [])
            if rid in got:
                assert got[rid] == want, (seed, rid, K, bs, nb)
                assert stream == want, (seed, rid)
            else:
                assert stream == want[:len(stream)], (seed, rid)
        assert eng.blocks_in_use == 0
        m = eng.metrics()
        assert m["blocks_allocated"] == m["blocks_released"]

    def test_moe_target_plain_and_spec_ragged(self):
        """ErnieMoe's new decode_ragged path on the unified engine: a
        plain (non-spec) ragged run AND a GPT-drafted spec run over the
        same MoE target both match the MoE's solo generation — the
        mixin-contract coverage for the non-GPT family."""
        from paddle_tpu.models.ernie_moe import (ErnieMoeConfig,
                                                 ErnieMoeModel)
        paddle.seed(41)
        cfg = ErnieMoeConfig(vocab_size=97, hidden_size=32, num_layers=2,
                             num_attention_heads=4, num_experts=4,
                             top_k=2, max_position_embeddings=96,
                             compute_dtype="float32")
        moe = ErnieMoeModel(cfg)
        mparams = {n: p._data for n, p in moe.named_parameters()}
        paddle.seed(79)
        dcfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=1,
                         num_attention_heads=4,
                         max_position_embeddings=96,
                         compute_dtype="float32")
        draft = GPTModel(dcfg)
        dparams = {n: p._data for n, p in draft.named_parameters()}
        for kw in ({}, dict(draft_model=draft, draft_params=dparams,
                            draft_k=2)):
            eng = RaggedPagedContinuousBatchingEngine(
                moe, mparams, max_slots=2, max_len=48, block_size=4,
                prompt_buckets=[8], **kw)
            rids = [eng.add_request(p, n)
                    for p, n in zip(PROMPTS[:3], (7, 5, 6))]
            got = eng.run_to_completion(max_ticks=300)
            for rid, p, n in zip(rids, PROMPTS[:3], (7, 5, 6)):
                assert got[rid] == _solo_greedy(moe, mparams, p, n), \
                    (bool(kw), rid)
            assert eng.blocks_in_use == 0

    def test_spec_true_needs_draft_and_guards(self, model_and_params):
        model, params = model_and_params
        eng = RaggedPagedContinuousBatchingEngine(
            model, params, max_slots=2, max_len=32, block_size=4,
            prompt_buckets=[8])
        with pytest.raises(ValueError, match="draft_model"):
            eng.add_request([1, 2, 3], 4, spec=True)
        paddle.seed(78)
        dcfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=1,
                         num_attention_heads=4,
                         max_position_embeddings=96,
                         compute_dtype="float32")
        draft = GPTModel(dcfg)
        dparams = {n: p._data for n, p in draft.named_parameters()}
        with pytest.raises(NotImplementedError, match="greedy-only"):
            RaggedPagedContinuousBatchingEngine(
                model, params, max_slots=2, max_len=32, block_size=4,
                prompt_buckets=[8], draft_model=draft,
                draft_params=dparams, per_request_sampling=True)
        with pytest.raises(NotImplementedError, match="repetition"):
            RaggedPagedContinuousBatchingEngine(
                model, params, max_slots=2, max_len=32, block_size=4,
                prompt_buckets=[8], draft_model=draft,
                draft_params=dparams, repetition_penalty=2.0)
        # over-proposal slack is charged on spec requests only
        spec_eng = RaggedPagedContinuousBatchingEngine(
            model, params, max_slots=1, max_len=20, block_size=4,
            prompt_buckets=[8], draft_model=draft, draft_params=dparams,
            draft_k=4)
        with pytest.raises(ValueError, match="exceeds max_len"):
            spec_eng.add_request([1, 2, 3], 10)    # 8 + 10 + 3 > 20
        spec_eng.add_request([1, 2, 3], 10, spec=False)   # plain: fits


class TestRaggedFuzz:
    @pytest.mark.slow
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_scenarios_match_solo(self, seed):
        """Randomized mixed-batch stress: random prompts/budgets/arrival
        times under randomly drawn engine configs INCLUDING tight pools
        (deferral + preemption), token budgets, prefix caching, penalty,
        eos, and int8 — every request's tokens must equal solo generate()
        with the same knobs, and the allocator must quiesce clean."""
        rng = np.random.RandomState(seed)
        kv = "int8" if rng.rand() < 0.5 else None
        paddle.seed(11)
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=96,
                        compute_dtype="float32", kv_cache_dtype=kv)
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}

        penalty = float(rng.choice([1.0, 4.0]))
        eos = int(rng.randint(0, 97)) if rng.rand() < 0.5 else None
        bs = int(rng.choice([2, 4, 8]))
        budget = int(rng.choice([6, 10, 16]))
        prefix = bool(rng.rand() < 0.5)
        slots = int(rng.randint(1, 4))
        budget = max(budget, slots)
        # worst single request: bucket 16 + decode budget of 11
        worst = -(-(16 + 11 - 1) // bs)
        nb = int(rng.randint(worst, worst * 3))
        eng = RaggedPagedContinuousBatchingEngine(
            model, params, max_slots=slots, max_len=48, block_size=bs,
            num_blocks=nb, prompt_buckets=[8, 16], token_budget=budget,
            enable_prefix_cache=prefix, repetition_penalty=penalty,
            eos_token_id=eos)

        sysp = [int(t) for t in rng.randint(1, 97, 9)]
        reqs = []
        for _ in range(int(rng.randint(4, 9))):
            p = (sysp + [int(t) for t in rng.randint(1, 97,
                                                     rng.randint(1, 6))]
                 if rng.rand() < 0.4 else
                 [int(t) for t in rng.randint(1, 97, rng.randint(1, 15))])
            n = int(rng.randint(1, 12))
            reqs.append((eng.add_request(p, n), p, n))
            for _ in range(int(rng.randint(0, 3))):
                eng.step()
        got = eng.run_to_completion(max_ticks=800)

        for rid, p, n in reqs:
            want = _solo_greedy(model, params, p, n,
                                repetition_penalty=penalty)
            if eos is not None and eos in want:
                want = want[:want.index(eos) + 1]
            assert got[rid] == want, (
                f"seed={seed} bs={bs} nb={nb} budget={budget} "
                f"penalty={penalty} eos={eos} kv={kv} prefix={prefix} "
                f"preempt={eng.preemptions}")
        if prefix:
            cached = sum(1 for b in eng._prefix_cache.values()
                         if eng._refs.get(b, 0) == 0)
            assert eng.blocks_in_use == cached
        else:
            assert eng.blocks_in_use == 0
