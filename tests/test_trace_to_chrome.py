"""tools/trace_to_chrome.py CLI contract: argument handling, the graceful
no-xprof failure path (actionable stderr + exit 1, never an ImportError
traceback), and the --engine-trace merge that lands serving-telemetry
spans next to XPlane device events in one chrome-trace file."""

import importlib.util
import json
import pathlib
import sys
import types

import pytest

TOOL = (pathlib.Path(__file__).parent.parent / "tools"
        / "trace_to_chrome.py")


@pytest.fixture()
def tool():
    spec = importlib.util.spec_from_file_location("trace_to_chrome", TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_missing_logdir_arg_is_an_error(tool, capsys):
    with pytest.raises(SystemExit) as ei:
        tool.main([])
    assert ei.value.code == 2                    # argparse usage error
    assert "logdir" in capsys.readouterr().err


def test_help_exits_zero(tool, capsys):
    with pytest.raises(SystemExit) as ei:
        tool.main(["--help"])
    assert ei.value.code == 0
    out = capsys.readouterr().out
    assert "--engine-trace" in out and "-o" in out


def test_empty_logdir_fails_with_message(tool, tmp_path, capsys):
    rc = tool.main([str(tmp_path)])
    assert rc == 1
    assert "no *.xplane.pb" in capsys.readouterr().err


def test_missing_xprof_fails_gracefully(tool, tmp_path, capsys,
                                        monkeypatch):
    """With a trace present but xprof uninstalled: exit 1 plus an
    actionable install hint on stderr — not a traceback."""
    (tmp_path / "host.xplane.pb").write_bytes(b"\x00")
    real_import = __import__

    def no_xprof(name, *a, **kw):
        if name.startswith("xprof"):
            raise ImportError("No module named 'xprof'")
        return real_import(name, *a, **kw)

    monkeypatch.delitem(sys.modules, "xprof", raising=False)
    monkeypatch.delitem(sys.modules, "xprof.convert", raising=False)
    monkeypatch.setattr("builtins.__import__", no_xprof)
    rc = tool.main([str(tmp_path)])
    assert rc == 1
    err = capsys.readouterr().err
    assert "xprof" in err and "pip install" in err


def _fake_xprof(monkeypatch, payload):
    rtd = types.ModuleType("xprof.convert.raw_to_tool_data")
    rtd.xspace_to_tool_data = lambda paths, tool, opts: (payload, "json")
    convert = types.ModuleType("xprof.convert")
    convert.raw_to_tool_data = rtd
    xprof = types.ModuleType("xprof")
    xprof.convert = convert
    monkeypatch.setitem(sys.modules, "xprof", xprof)
    monkeypatch.setitem(sys.modules, "xprof.convert", convert)
    monkeypatch.setitem(sys.modules, "xprof.convert.raw_to_tool_data", rtd)


def test_conversion_writes_output(tool, tmp_path, monkeypatch, capsys):
    (tmp_path / "host.xplane.pb").write_bytes(b"\x00")
    _fake_xprof(monkeypatch,
                json.dumps({"traceEvents": [{"name": "dev", "ph": "X"}]}))
    out = tmp_path / "trace.json"
    rc = tool.main([str(tmp_path), "-o", str(out)])
    assert rc == 0
    assert json.loads(out.read_text())["traceEvents"][0]["name"] == "dev"
    assert str(out) in capsys.readouterr().out


def test_engine_trace_merge(tool, tmp_path, monkeypatch):
    """Device events + engine telemetry (both input forms) end up in ONE
    traceEvents list."""
    (tmp_path / "host.xplane.pb").write_bytes(b"\x00")
    _fake_xprof(monkeypatch,
                json.dumps({"traceEvents": [{"name": "dev", "ph": "X"}]}))
    # chrome-JSON form
    eng_json = tmp_path / "engine.json"
    eng_json.write_text(json.dumps(
        {"traceEvents": [{"name": "tick", "ph": "X", "ts": 0, "dur": 1}]}))
    out = tmp_path / "merged.json"
    assert tool.main([str(tmp_path), "-o", str(out),
                      "--engine-trace", str(eng_json)]) == 0
    names = {e["name"] for e in json.loads(out.read_text())["traceEvents"]}
    assert {"dev", "tick"} <= names
    # JSONL form (Tracer.dump_jsonl shape)
    eng_jsonl = tmp_path / "engine.jsonl"
    eng_jsonl.write_text(
        json.dumps({"kind": "tick", "ts": 0.5, "engine": "E",
                    "dur_s": 0.01}) + "\n"
        + json.dumps({"kind": "compile", "ts": 0.2, "engine": "E",
                      "key": "decode:4", "hit": False,
                      "wall_s": 0.1}) + "\n")
    out2 = tmp_path / "merged2.json"
    assert tool.main([str(tmp_path), "-o", str(out2),
                      "--engine-trace", str(eng_jsonl)]) == 0
    names2 = {e["name"] for e in json.loads(out2.read_text())["traceEvents"]}
    assert "dev" in names2 and "tick" in names2
    assert any(n.startswith("compile:") for n in names2)
    # SINGLE-line JSONL parses as one dict — must still route to the
    # JSONL converter (the 'kind' field marks it), not be mistaken for
    # an already-converted chrome trace and silently dropped
    one = tmp_path / "one.jsonl"
    one.write_text(json.dumps({"kind": "tick", "ts": 0.1, "engine": "E",
                               "dur_s": 0.01}) + "\n")
    out3 = tmp_path / "merged3.json"
    assert tool.main([str(tmp_path), "-o", str(out3),
                      "--engine-trace", str(one)]) == 0
    names3 = {e["name"] for e in json.loads(out3.read_text())["traceEvents"]}
    assert "dev" in names3 and "tick" in names3


def test_ledger_counter_track_merge(tool, tmp_path, monkeypatch):
    """--ledger merges a RunLedger.dump_json payload as cumulative counter
    ("C") events next to the device rows."""
    from paddle_tpu.telemetry_ledger import RunLedger
    (tmp_path / "host.xplane.pb").write_bytes(b"\x00")
    _fake_xprof(monkeypatch,
                json.dumps({"traceEvents": [{"name": "dev", "ph": "X"}]}))
    led = RunLedger()
    led.record("compute", 0.2)
    led.record("data_wait", 0.1)
    dump = tmp_path / "goodput.json"
    led.dump_json(str(dump))
    out = tmp_path / "merged.json"
    assert tool.main([str(tmp_path), "-o", str(out),
                      "--ledger", str(dump)]) == 0
    evs = json.loads(out.read_text())["traceEvents"]
    counters = [e for e in evs if e.get("ph") == "C"]
    assert len(counters) == 2
    assert counters[-1]["args"]["compute"] == pytest.approx(0.2)
    assert counters[-1]["args"]["data_wait"] == pytest.approx(0.1)
    assert any(e.get("name") == "dev" for e in evs)
