"""Native slot-file parser (csrc/slot_feed.cpp ≙ reference
framework/data_feed.cc MultiSlotDataFeed) — python-oracle parity."""

import os
import time

import numpy as np
import pytest

from paddle_tpu.io.slot_feed import native_available, parse_dense_file
from paddle_tpu.io.dataset import InMemoryDataset, _default_parse

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="no native toolchain")


def _write(tmp_path, name, rows, cols, seed=0, fmt="%.6g"):
    rng = np.random.RandomState(seed)
    feats = rng.standard_normal((rows, cols - 1)).astype("float64")
    labels = rng.randint(0, 10, (rows,))
    path = os.path.join(tmp_path, name)
    with open(path, "w") as f:
        for r in range(rows):
            f.write(" ".join(fmt % v for v in feats[r]) + f" {labels[r]}\n")
    return path, feats, labels


class TestSlotFeed:
    def test_parity_with_python_parser(self, tmp_path):
        path, _, _ = _write(str(tmp_path), "a.txt", 37, 5)
        feats, labels = parse_dense_file(path, threads=3)
        with open(path) as f:
            oracle = [_default_parse(l.rstrip("\n")) for l in f]
        ofeats = np.stack([o[0] for o in oracle])
        olabels = np.asarray([o[1] for o in oracle])
        np.testing.assert_allclose(feats, ofeats, rtol=1e-6)
        np.testing.assert_array_equal(labels, olabels)

    def test_exponent_notation_and_blank_lines(self, tmp_path):
        path = os.path.join(str(tmp_path), "e.txt")
        with open(path, "w") as f:
            f.write("1.5e-3 -2E2 7\n\n   \n0.25 +1e1 3\n")
        feats, labels = parse_dense_file(path)
        np.testing.assert_allclose(feats, [[1.5e-3, -200.0], [0.25, 10.0]],
                                   rtol=1e-6)
        np.testing.assert_array_equal(labels, [7, 3])

    def test_malformed_raises(self, tmp_path):
        path = os.path.join(str(tmp_path), "bad.txt")
        with open(path, "w") as f:
            f.write("1.0 2.0 x\n")
        with pytest.raises(ValueError):
            parse_dense_file(path)

    def test_thread_counts_agree(self, tmp_path):
        path, _, _ = _write(str(tmp_path), "t.txt", 257, 4, seed=1)
        f1, l1 = parse_dense_file(path, threads=1)
        f8, l8 = parse_dense_file(path, threads=8)
        np.testing.assert_array_equal(f1, f8)
        np.testing.assert_array_equal(l1, l8)

    def test_dataset_trainer_uses_native_path(self, tmp_path):
        path, _, labels = _write(str(tmp_path), "ds.txt", 64, 9, seed=2)
        ds = InMemoryDataset()
        ds.set_filelist([path])
        ds.set_batch_size(16)
        ds.load_into_memory()
        batches = list(ds._batches_from(ds._example_stream()))
        assert len(batches) == 4
        got = np.concatenate([np.asarray(b[1]) for b in batches])
        np.testing.assert_array_equal(got, labels)

    def test_faster_than_python_on_bulk(self, tmp_path):
        path, _, _ = _write(str(tmp_path), "big.txt", 20000, 20, seed=3)

        t0 = time.perf_counter()
        parse_dense_file(path, threads=4)
        t_native = time.perf_counter() - t0

        t0 = time.perf_counter()
        with open(path) as f:
            for line in f:
                _default_parse(line.rstrip("\n"))
        t_python = time.perf_counter() - t0
        # loose 2x bound: the point is the native path is not a regression;
        # in practice it is ~20-50x
        assert t_native < t_python / 2, (t_native, t_python)


class TestSlotFeedStrictness:
    def test_digitless_tokens_rejected(self, tmp_path):
        for bad in ["1.0 . 3", "+ 2.0 3", "1e 2.0 3"]:
            path = os.path.join(str(tmp_path), "b.txt")
            with open(path, "w") as f:
                f.write(bad + "\n")
            with pytest.raises(ValueError):
                parse_dense_file(path)

    def test_ragged_extra_columns_rejected(self, tmp_path):
        path = os.path.join(str(tmp_path), "r.txt")
        with open(path, "w") as f:
            f.write("1 2 3\n1 2 3 4\n")
        with pytest.raises(ValueError):
            parse_dense_file(path)

    def test_empty_file_falls_back_to_zero_examples(self, tmp_path):
        path = os.path.join(str(tmp_path), "empty.txt")
        open(path, "w").close()
        ds = InMemoryDataset()
        ds.set_filelist([path])
        ds.load_into_memory()
        assert ds.get_memory_data_size() == 0

    def test_missing_file_raises_filenotfound(self, tmp_path):
        ds = InMemoryDataset()
        ds.set_filelist([os.path.join(str(tmp_path), "nope.txt")])
        with pytest.raises(FileNotFoundError):
            ds.load_into_memory()
