"""OpTest-grade oracle harness (≙ reference unittests/op_test.py:277,1104,1450).

Table-driven numeric verification of the public tensor/functional op surface:

- **Forward** vs an independent oracle (numpy; torch for special functions
  numpy lacks), at fp32 tolerances — and again at bf16 with loose tolerances
  for every float case that supports it (dtype tiers, ≙ op_test.py:1104).
- **Gradient** via central finite differences of the paddle forward itself vs
  ``paddle.grad`` (≙ op_test.py:1450 gradient_checker), fp32 only.
- **Coverage gate**: every public function of the covered modules must appear
  in the case table or the waiver list (with a reason), so new ops can't ship
  untested (the auto-discovery half of the reference's "every op has an
  OpTest" convention).
"""
from __future__ import annotations

import inspect
import zlib

import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

RNG = np.random.RandomState(20260730)


# --------------------------------------------------------------------------
# case table machinery
# --------------------------------------------------------------------------

class Case:
    """One op case: paddle path, positional inputs (ArraySpec or literals),
    kwargs, oracle fn over numpy inputs, and grad-check configuration."""

    def __init__(self, path, inputs, oracle, kwargs=None, grad=None,
                 bf16=True, rtol=None, atol=None, gtol=5e-2, key=None,
                 call=None):
        self.path = path
        self.inputs = inputs
        self.kwargs = kwargs or {}
        self.oracle = oracle
        # grad: indices of inputs to grad-check; None → all float specs
        self.grad = grad
        self.bf16 = bf16
        self.rtol = rtol
        self.atol = atol
        self.gtol = gtol
        self.call = call
        self.id = key or path + ("" if not self.kwargs else
                                 "-" + "-".join(f"{k}={v}" for k, v in
                                                sorted(self.kwargs.items())
                                                if not callable(v)))


class A:
    """Array input spec: shape + generator over the fp32 base draw."""

    def __init__(self, shape, gen=None, dtype="float32"):
        self.shape = tuple(shape)
        self.gen = gen
        self.dtype = dtype

    def draw(self):
        if self.dtype in ("int32", "int64"):
            x = RNG.randint(0, 5, self.shape).astype(self.dtype)
            if self.gen is not None:
                x = self.gen(x)
            return x
        if self.dtype == "bool":
            return RNG.rand(*self.shape) > 0.5
        x = np.asarray(RNG.randn(*self.shape)).astype("float32")
        if self.gen is not None:
            x = np.asarray(self.gen(x), dtype="float32")
        return x

    @property
    def is_float(self):
        return self.dtype == "float32"


def pos(x):       # strictly positive, away from 0
    return np.abs(x) + 0.5


def unit(x):      # open interval (-0.95, 0.95) — asin/atanh domains
    return np.tanh(x) * 0.95


def gt1(x):       # acosh domain
    return np.abs(x) + 1.5


def nokink(x):    # away from 0 so |.|-style kinks don't break finite diff
    return np.where(np.abs(x) < 0.25, x + 0.5 * np.sign(x) + 0.25, x)


def offint(x):    # away from integers (floor/ceil/round finite-diff safety)
    f = x - np.floor(x)
    return np.floor(x) + 0.3 + 0.4 * f


def _resolve(path):
    obj = {"paddle": paddle, "F": F, "linalg": paddle.linalg}[path.split(".")[0]]
    for part in path.split(".")[1:]:
        obj = getattr(obj, part)
    return obj


def _to_np(out):
    if isinstance(out, (tuple, list)):
        flat = []
        for o in out:
            flat.extend(_to_np(o))
        return flat
    if hasattr(out, "_data"):
        return [np.asarray(out._data)]
    return [np.asarray(out)]


def _torch(fn):
    """Wrap a torch fn as a numpy oracle."""
    def g(*xs):
        outs = fn(*[torch.from_numpy(np.asarray(x, "float64")) for x in xs])
        return outs.numpy()
    return g


# --------------------------------------------------------------------------
# compact constructors
# --------------------------------------------------------------------------

def U(name, np_fn, gen=None, grad=True, path=None, shape=(3, 4), **kw):
    return Case(path or f"paddle.{name}", [A(shape, gen)], np_fn,
                grad=[0] if grad else [], key=name, **kw)


def B(name, np_fn, gen=(None, None), shapes=((3, 4), (3, 4)), grad=True,
      path=None, **kw):
    return Case(path or f"paddle.{name}",
                [A(shapes[0], gen[0]), A(shapes[1], gen[1])], np_fn,
                grad=None if grad else [], key=name, **kw)


def IB(name, np_fn, path=None, **kw):   # integer binary (no grad)
    return Case(path or f"paddle.{name}",
                [A((3, 4), dtype="int32"), A((3, 4), lambda x: x + 1,
                                             dtype="int32")],
                np_fn, grad=[], bf16=False, key=name, **kw)


def R(name, np_fn, **kw):               # reduction with axis variants
    return [
        Case(f"paddle.{name}", [A((3, 4, 2))], np_fn, key=f"{name}-all", **kw),
        Case(f"paddle.{name}", [A((3, 4, 2))],
             lambda x, _f=np_fn: _f(x, axis=1), kwargs={"axis": 1},
             key=f"{name}-axis", **kw),
        Case(f"paddle.{name}", [A((3, 4, 2))],
             lambda x, _f=np_fn: _f(x, axis=(0, 2), keepdims=True),
             kwargs={"axis": (0, 2), "keepdim": True},
             key=f"{name}-keepdim", **kw),
    ]


# --------------------------------------------------------------------------
# oracles for paddle-specific semantics
# --------------------------------------------------------------------------

def np_sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def np_softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def np_logsumexp(x, axis=None, keepdims=False):
    m = np.max(x, axis=axis, keepdims=True)
    out = np.log(np.sum(np.exp(x - m), axis=axis, keepdims=True)) + m
    return out if keepdims else np.squeeze(out, axis=axis) if axis is not None \
        else out.reshape(())


# --------------------------------------------------------------------------
# the table
# --------------------------------------------------------------------------

IDX = A((4,), lambda x: np.array([3, 0, 2, 0]), dtype="int32")

CASES = [
    # ---------------- creation ------------------------------------------
    Case("paddle.arange", [], lambda: np.arange(2, 10, 1.5, dtype="float32"),
         kwargs={"start": 2, "end": 10, "step": 1.5, "dtype": "float32"},
         grad=[], key="arange"),
    Case("paddle.assign", [A((3, 4))], lambda x: x, key="assign"),
    Case("paddle.clone", [A((3, 4))], lambda x: x, key="clone"),
    Case("paddle.diag", [A((4,))], np.diag, key="diag-vec"),
    Case("paddle.diag", [A((3, 3))], np.diag, key="diag-mat"),
    Case("paddle.diagflat", [A((2, 3))], np.diagflat, key="diagflat"),
    Case("paddle.eye", [], lambda: np.eye(3, 5, dtype="float32"),
         kwargs={"num_rows": 3, "num_columns": 5}, grad=[], key="eye"),
    Case("paddle.full", [], lambda: np.full((2, 3), 2.5, "float32"),
         kwargs={"shape": (2, 3), "fill_value": 2.5}, grad=[], key="full"),
    Case("paddle.full_like", [A((2, 3))], lambda x: np.full_like(x, 7.0),
         kwargs={"fill_value": 7.0}, grad=[], key="full_like"),
    Case("paddle.linspace", [], lambda: np.linspace(0, 1, 7, dtype="float32"),
         kwargs={"start": 0, "stop": 1, "num": 7}, grad=[], key="linspace"),
    Case("paddle.logspace", [],
         lambda: np.logspace(0, 2, 5, dtype="float32"),
         kwargs={"start": 0, "stop": 2, "num": 5}, grad=[], key="logspace"),
    Case("paddle.meshgrid", [A((3,)), A((4,))],
         lambda a, b: list(np.meshgrid(a, b, indexing="ij")),
         grad=[], key="meshgrid"),
    Case("paddle.numel", [A((3, 4))], lambda x: np.asarray(x.size),
         grad=[], key="numel"),
    Case("paddle.ones", [], lambda: np.ones((2, 3), "float32"),
         kwargs={"shape": (2, 3)}, grad=[], key="ones"),
    Case("paddle.zeros", [], lambda: np.zeros((2, 3), "float32"),
         kwargs={"shape": (2, 3)}, grad=[], key="zeros"),
    Case("paddle.ones_like", [A((2, 3))], np.ones_like, grad=[],
         key="ones_like"),
    Case("paddle.zeros_like", [A((2, 3))], np.zeros_like, grad=[],
         key="zeros_like"),
    Case("paddle.tril", [A((4, 4))], np.tril, key="tril"),
    Case("paddle.triu", [A((4, 4))], lambda x: np.triu(x, 1),
         kwargs={"diagonal": 1}, key="triu"),
    Case("paddle.to_tensor", [A((3, 4))], lambda x: x, grad=[],
         key="to_tensor"),

    # ---------------- math: unary ---------------------------------------
    U("abs", np.abs, gen=nokink),
    U("acos", np.arccos, gen=unit),
    U("acosh", np.arccosh, gen=gt1),
    U("asin", np.arcsin, gen=unit),
    U("asinh", np.arcsinh),
    U("atan", np.arctan),
    U("atanh", np.arctanh, gen=unit),
    U("ceil", np.ceil, gen=offint, grad=False),
    U("conj", np.conj),
    U("cos", np.cos),
    U("cosh", np.cosh),
    U("deg2rad", np.deg2rad),
    U("digamma", _torch(torch.digamma), gen=pos, rtol=1e-4),
    U("erf", _torch(torch.erf)),
    U("erfinv", _torch(torch.erfinv), gen=unit, rtol=1e-4),
    U("exp", np.exp),
    U("expm1", np.expm1),
    U("floor", np.floor, gen=offint, grad=False),
    U("frac", lambda x: x - np.trunc(x), gen=offint),
    U("i0", _torch(torch.special.i0), rtol=1e-4),
    U("i1", _torch(torch.special.i1), rtol=1e-4),
    U("imag", np.imag, grad=False),
    U("real", np.real),
    U("lgamma", _torch(torch.lgamma), gen=pos, rtol=1e-4),
    U("log", np.log, gen=pos),
    U("log10", np.log10, gen=pos),
    U("log1p", np.log1p, gen=pos),
    U("log2", np.log2, gen=pos),
    U("neg", np.negative),
    U("rad2deg", np.rad2deg),
    U("reciprocal", np.reciprocal, gen=pos),
    U("round", np.round, gen=offint, grad=False),
    U("rsqrt", lambda x: 1.0 / np.sqrt(x), gen=pos),
    U("sigmoid", np_sigmoid),
    U("sign", np.sign, gen=nokink, grad=False),
    U("sin", np.sin),
    U("sinh", np.sinh),
    U("sqrt", np.sqrt, gen=pos),
    U("square", np.square),
    U("stanh", lambda x: 1.7159 * np.tanh(0.67 * x)),
    U("tan", np.tan, gen=unit),
    U("tanh", np.tanh),
    U("trunc", np.trunc, gen=offint, grad=False),
    U("angle", lambda x: np.angle(x).astype("float32"), gen=nokink,
      grad=False),
    U("isfinite", np.isfinite, grad=False, bf16=False),
    U("isinf", np.isinf, grad=False, bf16=False),
    U("isnan", np.isnan, grad=False, bf16=False),
    Case("paddle.increment", [A((1,))], lambda x: x + 1.0, key="increment"),
    Case("paddle.scale", [A((3, 4))], lambda x: 3.0 * x + 1.0,
         kwargs={"scale": 3.0, "bias": 1.0}, key="scale"),
    Case("paddle.scale", [A((3, 4))], lambda x: 3.0 * (x + 1.0),
         kwargs={"scale": 3.0, "bias": 1.0, "bias_after_scale": False},
         key="scale-bias-first"),
    Case("paddle.clip", [A((3, 4))], lambda x: np.clip(x, -0.5, 0.5),
         kwargs={"min": -0.5, "max": 0.5}, key="clip"),
    Case("paddle.pow", [A((3, 4), pos)], lambda x: x ** 2.5,
         kwargs={"y": 2.5}, key="pow-scalar"),

    # ---------------- math: binary --------------------------------------
    B("add", np.add),
    B("subtract", np.subtract),
    B("multiply", np.multiply),
    B("divide", np.divide, gen=(None, pos)),
    B("atan2", np.arctan2, gen=(nokink, pos)),
    B("copysign", np.copysign, gen=(nokink, nokink), grad=False),
    B("dist", lambda a, b: np.asarray(
        np.sqrt(np.sum((a - b) ** 2))).astype("float32")),
    B("floor_divide", lambda a, b: np.floor_divide(a, b),
      gen=(offint, pos), grad=False),
    B("floor_mod", lambda a, b: np.mod(a, b), gen=(offint, pos), grad=False),
    B("mod", lambda a, b: np.mod(a, b), gen=(offint, pos), grad=False),
    B("remainder", lambda a, b: np.mod(a, b), gen=(offint, pos), grad=False),
    B("fmax", np.fmax, gen=(nokink, lambda x: nokink(x) + 0.1)),
    B("fmin", np.fmin, gen=(nokink, lambda x: nokink(x) + 0.1)),
    B("heaviside", lambda a, b: np.heaviside(a, b), gen=(nokink, None),
      grad=False),
    B("hypot", np.hypot, gen=(pos, pos)),
    Case("paddle.ldexp",
         [A((3, 4)), A((3, 4), lambda x: x % 4 - 2, dtype="int32")],
         lambda a, b: np.ldexp(a, b), grad=[], bf16=False, key="ldexp"),
    B("logaddexp", np.logaddexp),
    B("maximum", np.maximum, gen=(nokink, lambda x: nokink(x) + 0.1)),
    B("minimum", np.minimum, gen=(nokink, lambda x: nokink(x) + 0.1)),
    B("nextafter", np.nextafter, grad=False, bf16=False),
    IB("gcd", np.gcd),
    IB("lcm", np.lcm),
    Case("paddle.lerp", [A((3, 4)), A((3, 4)), A((3, 4), np_sigmoid)],
         lambda a, b, w: a + w * (b - a), key="lerp"),
    Case("paddle.multiplex",
         [A((4, 3)), A((4, 3), lambda x: x + 1.0),
          A((4, 1), lambda x: np.array([[0], [1], [0], [1]]), dtype="int32")],
         lambda a, b, idx: np.stack([(a, b)[int(i)][r]
                                     for r, i in enumerate(idx.ravel())]),
         grad=[], key="multiplex",
         call=lambda fn, ts, kw: fn([ts[0], ts[1]], ts[2])),

    # ---------------- math: matmul family -------------------------------
    B("matmul", np.matmul, shapes=((3, 4), (4, 5))),
    B("mm", np.matmul, shapes=((3, 4), (4, 5))),
    B("bmm", np.matmul, shapes=((2, 3, 4), (2, 4, 5))),
    B("dot", lambda a, b: np.asarray(np.dot(a, b)), shapes=((5,), (5,))),
    B("inner", np.inner, shapes=((3, 4), (5, 4))),
    B("outer", np.outer, shapes=((3,), (4,))),
    B("mv", np.matmul, shapes=((3, 4), (4,))),
    B("kron", np.kron, shapes=((2, 3), (3, 2))),
    Case("paddle.addmm",
         [A((3, 5)), A((3, 4)), A((4, 5))],
         lambda i, x, y: 0.5 * i + 2.0 * (x @ y),
         kwargs={"beta": 0.5, "alpha": 2.0}, key="addmm"),
    Case("paddle.add_n", [A((3, 4)), A((3, 4)), A((3, 4))],
         lambda *xs: np.sum(xs, axis=0), grad=[], key="add_n",
         call=lambda fn, ts, kw: fn(list(ts))),

    # ---------------- math: reductions ----------------------------------
    *R("sum", np.sum),
    *R("mean", np.mean),
    *R("prod", np.prod, bf16=False),
    *R("max", np.max),
    *R("min", np.min),
    *R("amax", np.amax),
    *R("amin", np.amin),
    *R("logsumexp", np_logsumexp),
    Case("paddle.nanmean", [A((3, 4), lambda x: np.where(x > 1.2, np.nan, x))],
         lambda x: np.nanmean(x), grad=[], key="nanmean"),
    Case("paddle.nansum", [A((3, 4), lambda x: np.where(x > 1.2, np.nan, x))],
         lambda x: np.nansum(x), grad=[], key="nansum"),
    Case("paddle.count_nonzero", [A((3, 4), nokink)],
         lambda x: np.asarray(np.count_nonzero(x)), grad=[],
         key="count_nonzero"),
    Case("paddle.all", [A((3, 4), dtype="bool")],
         lambda x: np.asarray(np.all(x)), grad=[], bf16=False, key="all"),
    Case("paddle.any", [A((3, 4), dtype="bool")],
         lambda x: np.asarray(np.any(x)), grad=[], bf16=False, key="any"),
    Case("paddle.trace", [A((4, 4))], lambda x: np.asarray(np.trace(x)),
         key="trace"),

    # ---------------- math: scans ---------------------------------------
    Case("paddle.cumsum", [A((3, 4))], lambda x: np.cumsum(x, axis=1),
         kwargs={"axis": 1}, key="cumsum"),
    Case("paddle.cumprod", [A((3, 4), pos)], lambda x: np.cumprod(x, axis=1),
         kwargs={"dim": 1}, key="cumprod"),
    Case("paddle.cummax", [A((8,))],
         lambda x: [_torch(lambda t: torch.cummax(t, 0)[0])(x),
                    torch.cummax(torch.from_numpy(x), 0)[1].numpy()],
         grad=[], key="cummax"),
    Case("paddle.cummin", [A((8,))],
         lambda x: [_torch(lambda t: torch.cummin(t, 0)[0])(x),
                    torch.cummin(torch.from_numpy(x), 0)[1].numpy()],
         grad=[], key="cummin"),
    Case("paddle.diff", [A((3, 6))], lambda x: np.diff(x, axis=-1),
         key="diff"),

    # ---------------- math: meta / comparison-valued --------------------
    Case("paddle.allclose", [A((3, 4)), A((3, 4))],
         lambda a, b: np.asarray(np.allclose(a, b)), grad=[], bf16=False,
         key="allclose"),
    Case("paddle.isclose", [A((3, 4)), A((3, 4))],
         lambda a, b: np.isclose(a, b), grad=[], bf16=False, key="isclose"),
    Case("paddle.equal_all", [A((3, 4)), A((3, 4))],
         lambda a, b: np.asarray(np.array_equal(a, b)), grad=[], bf16=False,
         key="equal_all"),
    Case("paddle.broadcast_shape", [],
         lambda: [3, 4, 5],
         kwargs={"x_shape": (3, 1, 5), "y_shape": (4, 1)}, grad=[],
         bf16=False, key="broadcast_shape"),
    Case("paddle.take", [A((3, 4)), IDX],
         lambda x, i: x.ravel()[i], grad=[0], key="take"),

    # ---------------- logic ---------------------------------------------
    IB("bitwise_and", np.bitwise_and),
    IB("bitwise_or", np.bitwise_or),
    IB("bitwise_xor", np.bitwise_xor),
    IB("bitwise_left_shift", np.left_shift),
    IB("bitwise_right_shift", np.right_shift),
    Case("paddle.bitwise_not", [A((3, 4), dtype="int32")], np.bitwise_not,
         grad=[], bf16=False, key="bitwise_not"),
    B("equal", np.equal, grad=False, bf16=False),
    B("not_equal", np.not_equal, grad=False, bf16=False),
    B("greater_equal", np.greater_equal, grad=False, bf16=False),
    B("greater_than", np.greater, grad=False, bf16=False),
    B("less_equal", np.less_equal, grad=False, bf16=False),
    B("less_than", np.less, grad=False, bf16=False),
    Case("paddle.logical_and", [A((3, 4), dtype="bool"),
                                A((3, 4), dtype="bool")],
         np.logical_and, grad=[], bf16=False, key="logical_and"),
    Case("paddle.logical_or", [A((3, 4), dtype="bool"),
                               A((3, 4), dtype="bool")],
         np.logical_or, grad=[], bf16=False, key="logical_or"),
    Case("paddle.logical_xor", [A((3, 4), dtype="bool"),
                                A((3, 4), dtype="bool")],
         np.logical_xor, grad=[], bf16=False, key="logical_xor"),
    Case("paddle.logical_not", [A((3, 4), dtype="bool")], np.logical_not,
         grad=[], bf16=False, key="logical_not"),
    Case("paddle.is_empty", [A((0, 3))], lambda x: np.asarray(x.size == 0),
         grad=[], bf16=False, key="is_empty"),

    # ---------------- manipulation --------------------------------------
    Case("paddle.broadcast_to", [A((1, 4))],
         lambda x: np.broadcast_to(x, (3, 4)), kwargs={"shape": (3, 4)},
         key="broadcast_to"),
    Case("paddle.expand", [A((1, 4))],
         lambda x: np.broadcast_to(x, (3, 4)), kwargs={"shape": (3, 4)},
         key="expand"),
    Case("paddle.expand_as", [A((1, 4)), A((3, 4))],
         lambda x, y: np.broadcast_to(x, y.shape), grad=[0], key="expand_as"),
    Case("paddle.broadcast_tensors", [A((1, 4)), A((3, 1))],
         lambda a, b: list(np.broadcast_arrays(a, b)), grad=[],
         key="broadcast_tensors", call=lambda fn, ts, kw: fn(list(ts))),
    Case("paddle.atleast_1d", [A(())], np.atleast_1d, grad=[],
         key="atleast_1d"),
    Case("paddle.atleast_2d", [A((3,))], np.atleast_2d, key="atleast_2d"),
    Case("paddle.atleast_3d", [A((3, 4))], np.atleast_3d, key="atleast_3d"),
    Case("paddle.chunk", [A((6, 4))],
         lambda x: list(np.split(x, 3, axis=0)), kwargs={"chunks": 3},
         grad=[0], key="chunk"),
    Case("paddle.concat", [A((2, 4)), A((3, 4))],
         lambda a, b: np.concatenate([a, b], axis=0), grad=[],
         key="concat", call=lambda fn, ts, kw: fn(list(ts))),
    Case("paddle.crop", [A((4, 5))],
         lambda x: x[1:3, 2:5], kwargs={"shape": (2, 3), "offsets": (1, 2)},
         key="crop"),
    Case("paddle.flatten", [A((2, 3, 4))],
         lambda x: x.reshape(2, 12), kwargs={"start_axis": 1, "stop_axis": 2},
         key="flatten"),
    Case("paddle.flip", [A((3, 4))], lambda x: np.flip(x, axis=1),
         kwargs={"axis": 1}, key="flip"),
    Case("paddle.gather", [A((5, 3)), IDX],
         lambda x, i: x[i], grad=[0], key="gather"),
    Case("paddle.gather_nd", [A((4, 5)),
                              A((3, 2), lambda x: np.array(
                                  [[0, 1], [2, 3], [3, 4]]), dtype="int32")],
         lambda x, i: x[tuple(i.T)], grad=[0], key="gather_nd"),
    Case("paddle.index_add", [A((5, 3)), IDX, A((4, 3))],
         lambda x, i, v: _np_index_add(x, i, v), grad=[0, 2],
         key="index_add",
         call=lambda fn, ts, kw: fn(ts[0], ts[1], 0, ts[2])),
    Case("paddle.index_put",
         [A((5, 3)), A((2,), lambda x: np.array([1, 3]), dtype="int32"),
          A((2, 3))],
         lambda x, i, v: _np_scatter_overwrite(x, i, v), grad=[],
         key="index_put",
         call=lambda fn, ts, kw: fn(ts[0], (ts[1],), ts[2])),
    Case("paddle.index_select", [A((5, 3)), IDX],
         lambda x, i: x[i], kwargs={"axis": 0}, grad=[0],
         key="index_select"),
    Case("paddle.index_sample", [A((3, 5)),
                                 A((3, 2), lambda x: np.array(
                                     [[0, 1], [2, 3], [4, 0]]),
                                   dtype="int32")],
         lambda x, i: np.take_along_axis(x, i, axis=1), grad=[0],
         key="index_sample"),
    Case("paddle.masked_fill", [A((3, 4)), A((3, 4), dtype="bool")],
         lambda x, m: np.where(m, -2.0, x), kwargs={"value": -2.0},
         grad=[0], key="masked_fill"),
    Case("paddle.masked_select", [A((3, 4)),
                                  A((3, 4), dtype="bool")],
         lambda x, m: x[m], grad=[0], key="masked_select"),
    Case("paddle.moveaxis", [A((2, 3, 4))],
         lambda x: np.moveaxis(x, 0, 2),
         kwargs={"source": 0, "destination": 2}, key="moveaxis"),
    Case("paddle.pad", [A((3, 4))],
         lambda x: np.pad(x, ((0, 1), (1, 2))),
         kwargs={"pad": (0, 1, 1, 2)}, key="pad",
         gtol=8e-2),
    Case("paddle.put_along_axis",
         [A((3, 5)), A((3, 1), lambda x: np.array([[1], [2], [0]]),
                       dtype="int32"), A((3, 1))],
         lambda x, i, v: _np_put_along_axis(x, i, v),
         kwargs={"axis": 1}, grad=[0, 2], key="put_along_axis"),
    Case("paddle.repeat_interleave", [A((3, 4))],
         lambda x: np.repeat(x, 2, axis=1),
         kwargs={"repeats": 2, "axis": 1}, key="repeat_interleave"),
    Case("paddle.reshape", [A((3, 4))], lambda x: x.reshape(2, 6),
         kwargs={"shape": (2, 6)}, key="reshape"),
    Case("paddle.roll", [A((3, 4))], lambda x: np.roll(x, 2, axis=1),
         kwargs={"shifts": 2, "axis": 1}, key="roll"),
    Case("paddle.rot90", [A((3, 4))], lambda x: np.rot90(x),
         key="rot90"),
    Case("paddle.scatter",
         [A((5, 3)), A((2,), lambda x: np.array([1, 3]), dtype="int32"),
          A((2, 3))],
         lambda x, i, u: _np_scatter_overwrite(x, i, u), grad=[0, 2],
         key="scatter"),
    Case("paddle.scatter_nd",
         [A((3, 1), lambda x: np.array([[1], [3], [1]]), dtype="int32"),
          A((3, 4))],
         lambda i, u: _np_scatter_nd_add(np.zeros((6, 4), "float32"), i, u),
         kwargs={"shape": (6, 4)}, grad=[], key="scatter_nd"),
    Case("paddle.scatter_nd_add",
         [A((6, 4)), A((3, 1), lambda x: np.array([[1], [3], [1]]),
                       dtype="int32"), A((3, 4))],
         lambda x, i, u: _np_scatter_nd_add(x, i, u), grad=[0, 2],
         key="scatter_nd_add"),
    Case("paddle.shard_index",
         [A((4, 1), lambda x: np.array([[1], [6], [11], [15]]),
            dtype="int64")],
         lambda i: np.where((i >= 4) & (i < 8), i - 4, -1),
         kwargs={"index_num": 16, "nshards": 4, "shard_id": 1},
         grad=[], bf16=False, key="shard_index"),
    Case("paddle.slice", [A((3, 4, 5))],
         lambda x: x[:, 1:3, :],
         kwargs={"axes": [1], "starts": [1], "ends": [3]}, key="slice"),
    Case("paddle.split", [A((6, 4))],
         lambda x: list(np.split(x, [2, 5], axis=0)),
         kwargs={"num_or_sections": [2, 3, 1]}, grad=[0], key="split"),
    Case("paddle.squeeze", [A((3, 1, 4))], lambda x: np.squeeze(x, 1),
         kwargs={"axis": 1}, key="squeeze"),
    Case("paddle.stack", [A((3, 4)), A((3, 4))],
         lambda a, b: np.stack([a, b], axis=1),
         grad=[], key="stack", call=lambda fn, ts, kw: fn(list(ts), axis=1)),
    Case("paddle.strided_slice", [A((3, 8))],
         lambda x: x[:, 1:7:2],
         kwargs={"axes": [1], "starts": [1], "ends": [7], "strides": [2]},
         key="strided_slice"),
    Case("paddle.swapaxes", [A((2, 3, 4))], lambda x: np.swapaxes(x, 0, 2),
         kwargs={"axis0": 0, "axis1": 2}, key="swapaxes"),
    Case("paddle.t", [A((3, 4))], np.transpose, key="t"),
    Case("paddle.take_along_axis",
         [A((3, 5)), A((3, 2), lambda x: np.array([[0, 1], [2, 3], [4, 0]]),
                       dtype="int32")],
         lambda x, i: np.take_along_axis(x, i, axis=1),
         kwargs={"axis": 1}, grad=[0], key="take_along_axis"),
    Case("paddle.tensordot", [A((3, 4)), A((4, 5))],
         lambda a, b: np.tensordot(a, b, axes=1),
         kwargs={"axes": 1}, key="tensordot"),
    Case("paddle.tile", [A((2, 3))], lambda x: np.tile(x, (2, 2)),
         kwargs={"repeat_times": (2, 2)}, key="tile"),
    Case("paddle.transpose", [A((2, 3, 4))],
         lambda x: np.transpose(x, (2, 0, 1)), kwargs={"perm": (2, 0, 1)},
         key="transpose"),
    Case("paddle.unique",
         [A((8,), lambda x: np.array([3., 1., 2., 1., 3., 0., 2., 1.],
                                     "float32"))],
         lambda x: np.unique(x), grad=[], key="unique"),
    Case("paddle.unique_consecutive",
         [A((8,), lambda x: np.array([1., 1., 2., 2., 3., 1., 1., 0.],
                                     "float32"))],
         lambda x: np.array([1., 2., 3., 1., 0.], "float32"),
         grad=[], key="unique_consecutive"),
    Case("paddle.unsqueeze", [A((3, 4))], lambda x: x[:, None, :],
         kwargs={"axis": 1}, key="unsqueeze"),
    Case("paddle.unstack", [A((3, 4))],
         lambda x: [x[i] for i in range(3)], grad=[0], key="unstack"),
    Case("paddle.as_complex", [A((3, 4, 2))],
         lambda x: (x[..., 0] + 1j * x[..., 1]).astype("complex64"),
         grad=[], bf16=False, key="as_complex"),
    Case("paddle.view", [A((3, 4))], lambda x: x.reshape(2, 6),
         kwargs={"shape_or_dtype": (2, 6)}, key="view"),
    Case("paddle.view_as", [A((3, 4)), A((2, 6))],
         lambda x, y: x.reshape(y.shape), grad=[0], key="view_as"),

    # ---------------- linalg --------------------------------------------
    Case("linalg.cholesky", [A((4, 4), lambda x: x @ x.T + 4 * np.eye(4))],
         np.linalg.cholesky, grad=[], bf16=False, key="cholesky"),
    Case("linalg.det", [A((4, 4), lambda x: x + 2 * np.eye(4))],
         lambda x: np.asarray(np.linalg.det(x)), bf16=False, key="det", gtol=8e-2),
    Case("linalg.slogdet", [A((4, 4), lambda x: x + 3 * np.eye(4))],
         lambda x: np.stack(np.linalg.slogdet(x)), grad=[], bf16=False, key="slogdet"),
    Case("linalg.inv", [A((4, 4), lambda x: x + 3 * np.eye(4))],
         np.linalg.inv, grad=[], bf16=False, key="inv", rtol=1e-4),
    Case("linalg.inverse", [A((4, 4), lambda x: x + 3 * np.eye(4))],
         np.linalg.inv, grad=[], rtol=1e-4, bf16=False, key="inverse"),
    Case("linalg.matrix_power", [A((3, 3), lambda x: 0.5 * x)],
         lambda x: np.linalg.matrix_power(x, 3), kwargs={"n": 3},
         key="matrix_power"),
    Case("linalg.matrix_rank",
         [A((4, 4), lambda x: np.outer(x[0], x[1]))],
         lambda x: np.asarray(np.linalg.matrix_rank(x)), grad=[],
         bf16=False, key="matrix_rank"),
    Case("linalg.matrix_transpose", [A((2, 3, 4))],
         lambda x: np.swapaxes(x, -1, -2), key="matrix_transpose"),
    Case("linalg.multi_dot", [A((3, 4)), A((4, 5)), A((5, 2))],
         lambda a, b, c: a @ b @ c, grad=[], key="multi_dot",
         call=lambda fn, ts, kw: fn(list(ts))),
    Case("linalg.norm", [A((3, 4))],
         lambda x: np.asarray(np.linalg.norm(x)), key="norm-fro"),
    Case("linalg.norm", [A((6,))],
         lambda x: np.asarray(np.linalg.norm(x, 3)), kwargs={"p": 3},
         key="norm-p3"),
    Case("linalg.pinv", [A((4, 3))], np.linalg.pinv, grad=[],
         rtol=1e-4, bf16=False, key="pinv"),
    Case("linalg.solve",
         [A((4, 4), lambda x: x + 3 * np.eye(4)), A((4, 2))],
         np.linalg.solve, grad=[], rtol=1e-4, bf16=False, key="solve"),
    Case("linalg.triangular_solve",
         [A((3, 3), lambda x: np.tril(x) + 3 * np.eye(3)), A((3, 2))],
         lambda a, b: np.linalg.solve(a, b),
         kwargs={"upper": False}, grad=[], rtol=1e-4,
         bf16=False, key="triangular_solve"),
    Case("linalg.cholesky_solve",
         [A((3, 2)), A((3, 3), lambda x: np.linalg.cholesky(
             x @ x.T + 4 * np.eye(3)))],
         lambda b, L: np.linalg.solve(L @ L.T, b),
         kwargs={"upper": False}, grad=[], rtol=1e-4, bf16=False, key="cholesky_solve"),
    Case("linalg.eigvalsh", [A((4, 4), lambda x: (x + x.T) / 2)],
         lambda x: np.linalg.eigvalsh(x), grad=[], bf16=False, key="eigvalsh"),
    Case("linalg.cond", [A((4, 4), lambda x: x + 3 * np.eye(4))],
         lambda x: np.asarray(np.linalg.cond(x)), grad=[], rtol=1e-4,
         bf16=False, key="cond"),
    Case("linalg.cov", [A((3, 6))], np.cov, grad=[], key="cov"),
    Case("linalg.corrcoef", [A((3, 6))], np.corrcoef, grad=[],
         key="corrcoef"),
    Case("linalg.cross", [A((3, 3)), A((3, 3))],
         lambda a, b: np.cross(a, b, axisa=0, axisb=0, axisc=0),
         grad=None, key="cross"),
    Case("linalg.diagonal", [A((3, 4))],
         lambda x: np.diagonal(x), key="diagonal"),
    Case("linalg.histogram",
         [A((20,), lambda x: np.clip(x, -2.99, 2.99))],
         lambda x: np.histogram(x, bins=6, range=(-3, 3))[0],
         kwargs={"bins": 6, "min": -3, "max": 3}, grad=[], bf16=False,
         key="histogram"),
    Case("linalg.bincount",
         [A((10,), lambda x: np.array([0, 1, 1, 3, 2, 1, 7, 0, 0, 1]),
            dtype="int32")],
         lambda x: np.bincount(x), grad=[], bf16=False, key="bincount"),
    Case("paddle.einsum", [A((3, 4)), A((4, 5))],
         lambda a, b: np.einsum("ij,jk->ik", a, b), grad=[], key="einsum",
         call=lambda fn, ts, kw: fn("ij,jk->ik", *ts)),

    # ---------------- search --------------------------------------------
    Case("paddle.argmax", [A((3, 4))],
         lambda x: np.argmax(x, axis=1), kwargs={"axis": 1}, grad=[],
         bf16=False, key="argmax"),
    Case("paddle.argmin", [A((3, 4))],
         lambda x: np.argmin(x, axis=1), kwargs={"axis": 1}, grad=[],
         bf16=False, key="argmin"),
    Case("paddle.argsort", [A((3, 4))],
         lambda x: np.argsort(x, axis=1), kwargs={"axis": 1}, grad=[],
         bf16=False, key="argsort"),
    Case("paddle.sort", [A((3, 4))], lambda x: np.sort(x, axis=1),
         kwargs={"axis": 1}, grad=[0], key="sort"),
    Case("paddle.topk", [A((3, 6))],
         lambda x: [np.sort(x, axis=1)[:, :-3:-1],
                    np.argsort(x, axis=1)[:, :-3:-1]],
         kwargs={"k": 2}, grad=[], key="topk"),
    Case("paddle.kthvalue", [A((3, 6))],
         lambda x: [np.sort(x, axis=-1)[:, 1],
                    np.argsort(x, axis=-1)[:, 1]],
         kwargs={"k": 2}, grad=[], key="kthvalue"),
    Case("paddle.mode",
         [A((2, 5), lambda x: np.array([[1., 2., 2., 3., 2.],
                                        [0., 0., 1., 0., 4.]], "float32"))],
         lambda x: [np.array([2., 0.], "float32"),
                    np.array([4, 3])], grad=[], key="mode"),
    Case("paddle.nonzero",
         [A((2, 3), lambda x: np.array([[1., 0., 2.], [0., 3., 0.]],
                                       "float32"))],
         lambda x: np.argwhere(x), grad=[], bf16=False, key="nonzero"),
    Case("paddle.where", [A((3, 4), dtype="bool"), A((3, 4)), A((3, 4))],
         lambda c, a, b: np.where(c, a, b), grad=[1, 2], key="where"),
    Case("paddle.bucketize",
         [A((5,)), A((3,), lambda x: np.array([-1., 0., 1.], "float32"))],
         lambda x, e: np.searchsorted(e, x, side="left"), grad=[],
         bf16=False, key="bucketize"),
    Case("paddle.searchsorted",
         [A((4,), lambda x: np.sort(x)), A((5,))],
         lambda s, v: np.searchsorted(s, v, side="left"), grad=[],
         bf16=False, key="searchsorted"),
    Case("paddle.index_fill", [A((5, 3)),
                               A((2,), lambda x: np.array([1, 3]),
                                 dtype="int32")],
         lambda x, i: _np_index_fill(x, i, -1.0),
         kwargs={"axis": 0, "value": -1.0}, grad=[0], key="index_fill"),

    # ---------------- stat ----------------------------------------------
    Case("paddle.median", [A((3, 5))],
         lambda x: np.asarray(np.median(x)), grad=[], key="median"),
    Case("paddle.nanmedian", [A((3, 5), lambda x: np.where(x > 1.2,
                                                           np.nan, x))],
         lambda x: np.asarray(np.nanmedian(x)), grad=[], key="nanmedian"),
    Case("paddle.quantile", [A((3, 5))],
         lambda x: np.asarray(np.quantile(x, 0.25)), kwargs={"q": 0.25},
         grad=[], key="quantile"),
    Case("paddle.nanquantile", [A((3, 5), lambda x: np.where(x > 1.2,
                                                             np.nan, x))],
         lambda x: np.asarray(np.nanquantile(x, 0.5)), kwargs={"q": 0.5},
         grad=[], key="nanquantile"),
    Case("paddle.std", [A((3, 5))],
         lambda x: np.asarray(np.std(x, ddof=1)), key="std"),
    Case("paddle.var", [A((3, 5))],
         lambda x: np.asarray(np.var(x, ddof=1)), key="var"),
]


def _np_temporal_shift(x, seg_num, ratio):
    NT, C, H, W = x.shape
    N = NT // seg_num
    v = x.reshape(N, seg_num, C, H, W)
    fold = int(C * ratio)
    out = np.zeros_like(v)
    # reference temporal_shift_op.h:57-60 — first fold from the PAST
    # (src_it = it-1), second fold from the future (src_it = it+1)
    out[:, 1:, :fold] = v[:, :-1, :fold]
    out[:, :-1, fold:2 * fold] = v[:, 1:, fold:2 * fold]
    out[:, :, 2 * fold:] = v[:, :, 2 * fold:]
    return out.reshape(NT, C, H, W)


def _np_dice_loss(p, y, eps=1e-5):
    C = p.shape[-1]
    y1 = np.eye(C, dtype=p.dtype)[y.squeeze(-1)]
    axes = tuple(range(1, p.ndim))
    inter = 2 * (p * y1).sum(axis=axes)
    union = p.sum(axis=axes) + y1.sum(axis=axes)
    return np.asarray((1 - inter / (union + eps)).mean())


def _np_npair_loss(a, p, y, l2_reg=0.002):
    sim = a @ p.T
    y = y.reshape(-1, 1)
    tgt = (y == y.T).astype(a.dtype)
    tgt = tgt / tgt.sum(axis=1, keepdims=True)
    logp = sim - np_logsumexp(sim, axis=1, keepdims=True)
    xent = (-tgt * logp).sum(axis=1).mean()
    reg = l2_reg * ((a * a).sum(1).mean() + (p * p).sum(1).mean()) * 0.25
    return np.asarray(xent + reg, dtype="float32")


def _np_put_along_axis(x, i, v):
    out = x.copy()
    np.put_along_axis(out, i, v, axis=1)
    return out


def _np_index_add(x, i, v):
    out = x.copy()
    np.add.at(out, i, v)
    return out


def _np_index_fill(x, i, val):
    out = x.copy()
    out[i] = val
    return out


def _np_scatter_overwrite(x, i, u):
    out = x.copy()
    out[i] = u
    return out


def _np_scatter_nd_add(x, i, u):
    out = x.copy()
    np.add.at(out, tuple(i.T), u)
    return out


# --------------------------------------------------------------------------
# waivers: public fns NOT in the table, each with a reason
# --------------------------------------------------------------------------

WAIVERS = {
    # infra / aliases re-exported into op modules
    "apply": "dispatch plumbing, not an op",
    "convert_dtype": "dtype plumbing, covered implicitly by every case",
    "get_default_dtype": "config accessor",
    "check_shape": "arg validator",
    "tolist": "python-side accessor (tested via Tensor methods)",
    "empty": "value-unspecified; shape/dtype in test_tensor_ops TestRandomMoments",
    "empty_like": "value-unspecified by contract",
    "is_tensor": "type predicate, tested in test_api_surface",
    # random: statistical, seeded-draw determinism tested in test_tensor_ops
    "bernoulli": "moment-tested in test_tensor_ops TestRandomMoments", "bernoulli_": "statistical (random)",
    "binomial": "moment-tested in TestRandomMoments", "exponential_": "moment-tested in TestRandomMoments",
    "gaussian": "moment-tested in TestRandomMoments", "multinomial": "frequency-tested in TestRandomMoments",
    "normal": "moment-tested in TestRandomMoments", "normal_": "statistical (random)",
    "poisson": "moment-tested in TestRandomMoments", "rand": "statistical (random)",
    "randint": "statistical (random)", "randint_like": "statistical (random)",
    "randn": "statistical (random)", "randperm": "statistical (random)",
    "standard_normal": "moment-tested in TestRandomMoments",
    "uniform": "statistical (random)", "uniform_": "statistical (random)",
    # in-place aliases of covered ops
    "reshape_": "in-place alias of reshape", "scatter_": "in-place alias",
    "squeeze_": "in-place alias", "transpose_": "in-place alias",
    "unsqueeze_": "in-place alias", "tanh_": "in-place alias of tanh",
    "masked_fill_": "in-place alias", "where_": "in-place alias",
    # decomposition ops verified by reconstruction in test_tensor_ops
    "eig": "non-unique eigvectors; property-tested in TestDecompositionProperties",
    "eigvals": "complex order; property-tested in TestDecompositionProperties",
    "eigh": "sign-ambiguous; property-tested in TestDecompositionProperties",
    "qr": "sign-ambiguous; property-tested in TestDecompositionProperties",
    "svd": "sign-ambiguous; reconstruction-tested in test_tensor_ops (test_decompositions)",
    "lu": "pivot layout; property-tested in TestDecompositionProperties",
    "lstsq": "multi-output; property-tested in TestDecompositionProperties",
    "as_real": "inverse of as_complex (complex dtype input)",
    "conj": "real passthrough covered; complex in test_tensor_ops",
}


# --------------------------------------------------------------------------
# nn.functional tier: activations + losses vs paddle-documented formulas
# --------------------------------------------------------------------------

def FU(name, np_fn, gen=None, grad=True, **kw):
    """functional unary: F.<name> on a (3, 4) float input."""
    return U(name, np_fn, gen=gen, grad=grad, path=f"F.{name}", **kw)


def np_softplus(x, beta=1.0, threshold=20.0):
    return np.where(beta * x > threshold, x,
                    np.log1p(np.exp(beta * x)) / beta)


def np_gelu_erf(x):
    return 0.5 * x * (1.0 + _torch(torch.erf)(x / np.sqrt(2.0)))


F_CASES = [
    FU("relu", lambda x: np.maximum(x, 0), gen=nokink),
    FU("relu6", lambda x: np.clip(x, 0, 6), gen=nokink),
    FU("elu", lambda x: np.where(x > 0, x, np.exp(x) - 1.0), gen=nokink),
    FU("celu", lambda x: np.maximum(x, 0)
       + np.minimum(0, 2.0 * (np.exp(x / 2.0) - 1)), gen=nokink,
       kwargs={"alpha": 2.0}),
    FU("selu", lambda x: 1.0507009873554805 * np.where(
        x > 0, x, 1.6732632423543772 * (np.exp(x) - 1)), gen=nokink),
    FU("silu", lambda x: x * np_sigmoid(x)),
    FU("swish", lambda x: x * np_sigmoid(x)),
    FU("mish", lambda x: x * np.tanh(np_softplus(x))),
    FU("gelu", np_gelu_erf),
    Case("F.gelu", [A((3, 4))],
         lambda x: 0.5 * x * (1 + np.tanh(np.sqrt(2 / np.pi)
                                          * (x + 0.044715 * x ** 3))),
         kwargs={"approximate": True}, grad=None, key="gelu-tanh"),
    FU("hardsigmoid", lambda x: np.clip(x / 6.0 + 0.5, 0, 1),
       gen=lambda x: nokink(x) * 2),
    FU("hardswish", lambda x: x * np.clip(x + 3, 0, 6) / 6.0,
       gen=lambda x: nokink(x) * 2),
    FU("hardtanh", lambda x: np.clip(x, -1, 1), gen=lambda x: nokink(x) * 2),
    FU("hardshrink", lambda x: np.where(np.abs(x) > 0.5, x, 0), gen=nokink),
    FU("softshrink", lambda x: np.where(x > 0.5, x - 0.5,
                                        np.where(x < -0.5, x + 0.5, 0)),
       gen=nokink),
    FU("tanhshrink", lambda x: x - np.tanh(x)),
    FU("softsign", lambda x: x / (1 + np.abs(x)), gen=nokink),
    FU("softplus", np_softplus),
    Case("F.softplus", [A((3, 4))],
         lambda x: np_softplus(x, beta=2.0, threshold=10.0),
         kwargs={"beta": 2.0, "threshold": 10.0}, grad=None,
         key="softplus-beta"),
    FU("log_sigmoid", lambda x: -np_softplus(-x)),
    FU("leaky_relu", lambda x: np.where(x > 0, x, 0.01 * x), gen=nokink),
    FU("thresholded_relu", lambda x: np.where(x > 1.0, x, 0.0), gen=nokink),
    FU("sigmoid", np_sigmoid),
    FU("tanh", np.tanh),
    Case("F.softmax", [A((3, 6))], lambda x: np_softmax(x, axis=-1),
         key="softmax"),
    Case("F.log_softmax", [A((3, 6))],
         lambda x: np.log(np_softmax(x, axis=-1)), key="log_softmax"),
    Case("F.prelu", [A((2, 3, 4), nokink), A((3,), pos)],
         lambda x, w: np.where(x > 0, x, w[None, :, None] * x),
         grad=[0], key="prelu"),
    Case("F.maxout", [A((2, 4, 3, 3))],
         lambda x: x.reshape(2, 2, 2, 3, 3).max(axis=2),
         kwargs={"groups": 2}, grad=[], key="maxout"),
    Case("F.glu", [A((3, 8))],
         lambda x: x[:, :4] * np_sigmoid(x[:, 4:]), key="glu"),
    Case("F.normalize", [A((3, 4), pos)],
         lambda x: x / np.sqrt((x ** 2).sum(-1, keepdims=True)),
         key="normalize"),
    Case("F.cosine_similarity", [A((3, 4)), A((3, 4))],
         lambda a, b: (a * b).sum(-1) / (np.sqrt((a ** 2).sum(-1))
                                         * np.sqrt((b ** 2).sum(-1))),
         key="cosine_similarity"),
    Case("F.one_hot", [A((4,), lambda x: np.array([0, 2, 1, 3]),
                         dtype="int32")],
         lambda i: np.eye(5, dtype="float32")[i],
         kwargs={"num_classes": 5}, grad=[], bf16=False, key="one_hot"),
    Case("F.label_smooth", [A((3, 5), lambda x: np.abs(x))],
         lambda x: 0.9 * x + 0.1 / 5, kwargs={"epsilon": 0.1},
         key="label_smooth"),
    Case("F.sequence_mask", [A((3,), lambda x: np.array([1, 3, 2]),
                              dtype="int32")],
         lambda l: (np.arange(3)[None, :] < l[:, None]),
         kwargs={"maxlen": 3}, grad=[], bf16=False, key="sequence_mask"),
    Case("F.linear", [A((3, 4)), A((4, 5)), A((5,))],
         lambda x, w, b: x @ w + b, key="linear"),
    Case("F.embedding", [A((5,), lambda x: np.array([0, 2, 1, 4, 3]),
                           dtype="int32"), A((6, 4))],
         lambda i, w: w[i], grad=[1], key="embedding"),
    Case("F.diag_embed", [A((2, 3))],
         lambda x: np.stack([np.diag(r) for r in x]), key="diag_embed"),
    Case("F.pixel_shuffle", [A((1, 4, 2, 2))],
         lambda x: torch.pixel_shuffle(torch.from_numpy(x), 2).numpy(),
         kwargs={"upscale_factor": 2}, grad=None, key="pixel_shuffle"),
    # ---------------- losses --------------------------------------------
    Case("F.mse_loss", [A((3, 4)), A((3, 4))],
         lambda a, b: np.asarray(((a - b) ** 2).mean()), key="mse_loss"),
    Case("F.l1_loss", [A((3, 4)), A((3, 4))],
         lambda a, b: np.asarray(np.abs(a - b).mean()), key="l1_loss",
         gtol=8e-2),
    Case("F.square_error_cost", [A((3, 4)), A((3, 4))],
         lambda a, b: (a - b) ** 2, key="square_error_cost"),
    Case("F.log_loss", [A((4, 1), lambda x: np_sigmoid(x) * 0.9 + 0.05),
                        A((4, 1), lambda x: (x > 0).astype("float32"))],
         lambda p, y: -y * np.log(p + 1e-4) - (1 - y) * np.log(1 - p + 1e-4),
         grad=[0], key="log_loss"),
    Case("F.smooth_l1_loss", [A((3, 4)), A((3, 4),
                                           lambda x: x + 2.5)],
         lambda a, b: np.asarray(np.where(
             np.abs(a - b) < 1.0, 0.5 * (a - b) ** 2,
             np.abs(a - b) - 0.5).mean()), grad=[0], key="smooth_l1_loss"),
    Case("F.binary_cross_entropy",
         [A((3, 4), lambda x: np_sigmoid(x) * 0.9 + 0.05),
          A((3, 4), lambda x: (x > 0).astype("float32"))],
         lambda p, y: np.asarray(
             (-(y * np.log(p) + (1 - y) * np.log(1 - p))).mean()),
         grad=[0], key="binary_cross_entropy"),
    Case("F.binary_cross_entropy_with_logits",
         [A((3, 4)), A((3, 4), lambda x: (x > 0).astype("float32"))],
         lambda z, y: np.asarray(
             (np.maximum(z, 0) - z * y + np.log1p(np.exp(-np.abs(z)))).mean()),
         grad=[0], key="bce_with_logits"),
    Case("F.cross_entropy", [A((4, 5)),
                             A((4,), lambda x: np.array([0, 3, 1, 2]),
                               dtype="int32")],
         lambda z, y: np.asarray(
             -np.log(np_softmax(z, -1))[np.arange(4), y].mean()),
         grad=[0], key="cross_entropy"),
    Case("F.nll_loss", [A((4, 5), lambda x: np.log(np_softmax(x, -1))),
                        A((4,), lambda x: np.array([0, 3, 1, 2]),
                          dtype="int32")],
         lambda lp, y: np.asarray(-lp[np.arange(4), y].mean()),
         grad=[0], key="nll_loss"),
    Case("F.kl_div", [A((3, 4), lambda x: np.log(np_softmax(x, -1))),
                      A((3, 4), lambda x: np_softmax(x, -1))],
         lambda lp, t: np.asarray((t * (np.log(t) - lp)).mean()),
         grad=[0], key="kl_div"),
    Case("F.margin_ranking_loss", [A((4,)), A((4,)),
                                   A((4,), lambda x: np.sign(nokink(x)))],
         lambda a, b, y: np.asarray(np.maximum(0, -y * (a - b) + 0.0).mean()),
         grad=[0, 1], key="margin_ranking_loss"),
    Case("F.cosine_embedding_loss",
         [A((3, 4)), A((3, 4)), A((3,), lambda x: np.array([1., -1., 1.]))],
         lambda a, b, y: np.asarray(np.where(
             y > 0,
             1 - (a * b).sum(-1) / (np.linalg.norm(a, axis=-1)
                                    * np.linalg.norm(b, axis=-1)),
             np.maximum(0, (a * b).sum(-1)
                        / (np.linalg.norm(a, axis=-1)
                           * np.linalg.norm(b, axis=-1)))).mean()),
         grad=[], key="cosine_embedding_loss"),
    Case("F.hinge_embedding_loss", [A((3, 4), nokink),
                                    A((3, 4), lambda x: np.sign(nokink(x)))],
         lambda x, y: np.asarray(np.where(
             y > 0, x, np.maximum(0, 1.0 - x)).mean()),
         grad=[0], key="hinge_embedding_loss"),
    Case("F.triplet_margin_loss", [A((3, 4)), A((3, 4), lambda x: x + 1),
                                   A((3, 4), lambda x: x - 1)],
         lambda a, p, n: np.asarray(np.maximum(
             np.linalg.norm(a - p, axis=-1)
             - np.linalg.norm(a - n, axis=-1) + 1.0, 0).mean()),
         grad=[], key="triplet_margin_loss"),
    Case("F.sigmoid_focal_loss",
         [A((3, 4)), A((3, 4), lambda x: (x > 0).astype("float32"))],
         lambda z, y: np.asarray((
             -(y * np.log(np_sigmoid(z)) + (1 - y) * np.log(1 - np_sigmoid(z)))
             * ((y * (1 - np_sigmoid(z)) + (1 - y) * np_sigmoid(z)) ** 2.0)
             * (y * 0.25 + (1 - y) * 0.75)).sum()),
         grad=[0], gtol=8e-2, key="sigmoid_focal_loss"),
    Case("F.dropout", [A((64, 64))], lambda x: x,
         kwargs={"p": 0.0}, grad=[], key="dropout-p0"),
    Case("F.pad", [A((3, 4))],
         lambda x: np.pad(x, ((0, 0), (1, 2))),
         kwargs={"pad": (1, 2)}, key="f_pad"),
]

CASES.extend(F_CASES)


# --------------------------------------------------------------------------
# conv / pool / norm tier: torch as the oracle (identical public contracts)
# --------------------------------------------------------------------------

def _t(x):
    return torch.from_numpy(np.asarray(x, "float64"))


CONV_CASES = [
    Case("F.conv2d", [A((2, 3, 8, 8)), A((5, 3, 3, 3))],
         lambda x, w: torch.nn.functional.conv2d(_t(x), _t(w)).numpy(),
         grad=None, key="conv2d-basic", gtol=8e-2),
    Case("F.conv2d", [A((2, 3, 8, 8)), A((5, 3, 3, 3)), A((5,))],
         lambda x, w, b: torch.nn.functional.conv2d(
             _t(x), _t(w), _t(b), stride=2, padding=1).numpy(),
         kwargs={"stride": 2, "padding": 1}, grad=[0], key="conv2d-stride",
         gtol=8e-2),
    Case("F.conv2d", [A((2, 4, 6, 6)), A((4, 2, 3, 3))],
         lambda x, w: torch.nn.functional.conv2d(
             _t(x), _t(w), groups=2).numpy(),
         kwargs={"groups": 2}, grad=[0], key="conv2d-groups", gtol=8e-2),
    Case("F.conv2d", [A((1, 2, 7, 7)), A((3, 2, 3, 3))],
         lambda x, w: torch.nn.functional.conv2d(
             _t(x), _t(w), dilation=2).numpy(),
         kwargs={"dilation": 2}, grad=[], key="conv2d-dilation"),
    Case("F.conv1d", [A((2, 3, 9)), A((4, 3, 3))],
         lambda x, w: torch.nn.functional.conv1d(_t(x), _t(w)).numpy(),
         grad=[0], key="conv1d", gtol=8e-2),
    Case("F.conv2d_transpose", [A((2, 3, 5, 5)), A((3, 4, 3, 3))],
         lambda x, w: torch.nn.functional.conv_transpose2d(
             _t(x), _t(w), stride=2).numpy(),
         kwargs={"stride": 2}, grad=[], key="conv2d_transpose"),
    Case("F.max_pool2d", [A((2, 3, 8, 8))],
         lambda x: torch.nn.functional.max_pool2d(_t(x), 2).numpy(),
         kwargs={"kernel_size": 2}, grad=[], key="max_pool2d"),
    Case("F.max_pool2d", [A((2, 3, 9, 9))],
         lambda x: torch.nn.functional.max_pool2d(
             _t(x), 3, stride=2, padding=1).numpy(),
         kwargs={"kernel_size": 3, "stride": 2, "padding": 1}, grad=[],
         key="max_pool2d-pad"),
    Case("F.avg_pool2d", [A((2, 3, 8, 8))],
         lambda x: torch.nn.functional.avg_pool2d(_t(x), 2).numpy(),
         kwargs={"kernel_size": 2}, grad=[0], key="avg_pool2d"),
    Case("F.adaptive_avg_pool2d", [A((2, 3, 8, 8))],
         lambda x: torch.nn.functional.adaptive_avg_pool2d(_t(x), 4).numpy(),
         kwargs={"output_size": 4}, grad=[0], key="adaptive_avg_pool2d"),
    Case("F.adaptive_max_pool2d", [A((2, 3, 8, 8))],
         lambda x: torch.nn.functional.adaptive_max_pool2d(_t(x), 2).numpy(),
         kwargs={"output_size": 2}, grad=[], key="adaptive_max_pool2d"),
    Case("F.layer_norm", [A((4, 6)), A((6,), pos), A((6,))],
         lambda x, w, b: torch.nn.functional.layer_norm(
             _t(x), (6,), _t(w), _t(b)).numpy(),
         kwargs={"normalized_shape": (6,)}, grad=None, key="layer_norm",
         call=lambda fn, ts, kw: fn(ts[0], (6,), weight=ts[1], bias=ts[2])),
    Case("F.group_norm", [A((2, 6, 4, 4))],
         lambda x: torch.nn.functional.group_norm(_t(x), 3).numpy(),
         kwargs={"num_groups": 3}, grad=[0], key="group_norm"),
    Case("F.batch_norm",
         [A((4, 3, 5, 5)), A((3,)), A((3,), lambda x: np.abs(x) + 0.5),
          A((3,), pos), A((3,))],
         lambda x, m, v, w, b: torch.nn.functional.batch_norm(
             _t(x), _t(m), _t(v), _t(w), _t(b), False, 0.9, 1e-5).numpy(),
         grad=[0], key="batch_norm",
         call=lambda fn, ts, kw: fn(ts[0], ts[1], ts[2], weight=ts[3],
                                    bias=ts[4], training=False)),
    Case("F.instance_norm", [A((2, 3, 6, 6))],
         lambda x: torch.nn.functional.instance_norm(_t(x)).numpy(),
         grad=[0], key="instance_norm"),
    Case("F.interpolate", [A((1, 2, 4, 4))],
         lambda x: torch.nn.functional.interpolate(
             _t(x), scale_factor=2, mode="nearest").numpy(),
         kwargs={"scale_factor": 2, "mode": "nearest"}, grad=[0],
         key="interpolate-nearest"),
    Case("F.interpolate", [A((1, 2, 4, 4))],
         lambda x: torch.nn.functional.interpolate(
             _t(x), scale_factor=2, mode="bilinear",
             align_corners=True).numpy(),
         kwargs={"scale_factor": 2, "mode": "bilinear",
                 "align_corners": True}, grad=[],
         key="interpolate-bilinear"),
    Case("F.unfold", [A((1, 2, 6, 6))],
         lambda x: torch.nn.functional.unfold(_t(x), 3).numpy(),
         kwargs={"kernel_sizes": 3}, grad=[], key="unfold"),
    Case("F.cosine_similarity", [A((3, 8)), A((3, 8))],
         lambda a, b: torch.nn.functional.cosine_similarity(
             _t(a), _t(b)).numpy(), grad=None, key="cosine_similarity-t"),
    Case("F.embedding", [A((2, 3), lambda x: np.array([[0, 2, 1], [4, 3, 0]]),
                           dtype="int32"), A((6, 4))],
         lambda i, w: w[i], grad=[1], key="embedding-2d"),
    Case("F.bilinear", [A((4, 3)), A((4, 5)), A((2, 3, 5)), A((2,))],
         lambda a, b, w, bi: torch.nn.functional.bilinear(
             _t(a), _t(b), _t(w), _t(bi)).numpy(),
         grad=[0, 1], key="bilinear"),
    Case("F.local_response_norm", [A((2, 6, 4, 4))],
         # 2.x semantics = torch's: denom (k + alpha*mean(x^2 window))^beta
         lambda x: torch.nn.functional.local_response_norm(
             _t(x), 3, alpha=1e-4, beta=0.75, k=1.0).numpy(),
         kwargs={"size": 3}, grad=[0], key="local_response_norm"),
    Case("F.grid_sample",
         [A((1, 2, 4, 4)), A((1, 3, 3, 2), lambda x: np.tanh(x) * 0.9)],
         lambda x, g: torch.nn.functional.grid_sample(
             _t(x), _t(g), mode="bilinear", padding_mode="zeros",
             align_corners=True).numpy(),
         grad=[0], key="grid_sample"),
    Case("F.affine_grid",
         [A((2, 2, 3), lambda x: 0.2 * x + np.array([[1., 0., 0.],
                                                     [0., 1., 0.]]))],
         lambda th: torch.nn.functional.affine_grid(
             _t(th), (2, 1, 4, 5), align_corners=True).numpy(),
         kwargs={"out_shape": (2, 1, 4, 5), "align_corners": True},
         grad=[0], key="affine_grid"),
    Case("F.channel_shuffle", [A((2, 6, 3, 3))],
         lambda x: x.reshape(2, 2, 3, 3, 3).transpose(
             0, 2, 1, 3, 4).reshape(2, 6, 3, 3),
         kwargs={"groups": 2}, grad=[0], key="channel_shuffle"),
    Case("F.temporal_shift", [A((4, 4, 3, 3))],
         lambda x: _np_temporal_shift(x, seg_num=2, ratio=0.25),
         kwargs={"seg_num": 2, "shift_ratio": 0.25}, grad=[0],
         key="temporal_shift"),
    Case("F.ctc_loss",
         [A((6, 2, 5)),
          A((2, 3), lambda x: np.array([[1, 2, 1], [3, 4, 0]]),
            dtype="int32"),
          A((2,), lambda x: np.array([6, 5]), dtype="int32"),
          A((2,), lambda x: np.array([3, 2]), dtype="int32")],
         lambda lp, lab, il, ll: torch.nn.functional.ctc_loss(
             torch.log_softmax(_t(lp), -1),
             torch.from_numpy(lab.astype("int64")),
             torch.from_numpy(il.astype("int64")),
             torch.from_numpy(ll.astype("int64")), blank=0,
             reduction="mean").numpy(),
         grad=[0], gtol=8e-2, key="ctc_loss"),
    Case("F.dice_loss",
         [A((3, 4, 5), lambda x: np_softmax(x, -1)),
          A((3, 4, 1), lambda x: np.array(
              [[[0], [2], [1], [4]], [[3], [0], [2], [1]],
               [[4], [4], [0], [3]]]), dtype="int32")],
         lambda p, y: _np_dice_loss(p, y, eps=1e-2),
         kwargs={"epsilon": 1e-2}, grad=[0], key="dice_loss"),
    Case("F.npair_loss",
         [A((4, 6)), A((4, 6)),
          A((4,), lambda x: np.array([0, 1, 0, 2]), dtype="int32")],
         lambda a, pz, y: _np_npair_loss(a, pz, y), grad=[0, 1],
         key="npair_loss"),
]

CASES.extend(CONV_CASES)


# --------------------------------------------------------------------------
# fixtures / runners
# --------------------------------------------------------------------------

def _call_case(case, tensors):
    fn = _resolve(case.path)
    if case.call is not None:
        return case.call(fn, tensors, case.kwargs)
    return fn(*tensors, **case.kwargs)


def _run_paddle(case, np_inputs, dtype="float32"):
    tensors = []
    for spec, x in zip(case.inputs, np_inputs):
        if spec.is_float and dtype != "float32":
            t = paddle.to_tensor(x).astype(dtype)
        else:
            t = paddle.to_tensor(x)
        tensors.append(t)
    return _call_case(case, tensors)


def _expected(case, np_inputs):
    return case.oracle(*np_inputs)


@pytest.mark.parametrize("case", CASES, ids=lambda c: c.id)
def test_forward_fp32(case):
    np_inputs = [spec.draw() for spec in case.inputs]
    got = _to_np(_run_paddle(case, np_inputs))
    want = _to_np(_expected(case, np_inputs))
    assert len(got) == len(want), \
        f"{case.id}: {len(got)} outputs vs oracle {len(want)}"
    rtol = case.rtol or 1e-5
    atol = case.atol or 1e-5
    for g, w in zip(got, want):
        assert g.shape == np.asarray(w).shape, \
            f"{case.id}: shape {g.shape} vs {np.asarray(w).shape}"
        # complex outputs (as_complex etc.) compare in complex128 — a
        # float64 cast would drop the imaginary part (and warn)
        cmp = ("complex128" if np.iscomplexobj(np.asarray(g))
               or np.iscomplexobj(np.asarray(w)) else "float64")
        np.testing.assert_allclose(
            np.asarray(g, cmp), np.asarray(w, cmp),
            rtol=rtol, atol=atol, err_msg=case.id)


BF16_CASES = [c for c in CASES
              if c.bf16 and c.inputs and all(s.is_float for s in c.inputs)]


@pytest.mark.parametrize("case", BF16_CASES, ids=lambda c: c.id)
def test_forward_bf16(case):
    """bf16 tier (≙ op_test.py dtype tiers): same oracle, loose tolerance."""
    np_inputs = [spec.draw() for spec in case.inputs]
    got = _to_np(_run_paddle(case, np_inputs, dtype="bfloat16"))
    # oracle on bf16-rounded inputs, fp32 accumulate
    rounded = [np.asarray(x).astype("float32") for x in np_inputs]
    want = _to_np(_expected(case, rounded))
    for g, w in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g, "float64"), np.asarray(w, "float64"),
            rtol=4e-2, atol=4e-2, err_msg=case.id)


GRAD_CASES = []
for c in CASES:
    gi = c.grad if c.grad is not None else [
        i for i, s in enumerate(c.inputs) if s.is_float]
    if gi and all(c.inputs[i].is_float for i in gi):
        GRAD_CASES.append((c, gi))


@pytest.mark.parametrize("case,gi", GRAD_CASES, ids=lambda p: None if
                         isinstance(p, list) else p.id)
def test_grad_vs_finite_difference(case, gi):
    """Analytic grad (tape) vs central finite difference of the paddle
    forward — the gradient_checker half of op_test (op_test.py:1450)."""
    np_inputs = [spec.draw() for spec in case.inputs]

    def fwd(flat_list):
        tensors = []
        k = 0
        for i, (spec, x) in enumerate(zip(case.inputs, np_inputs)):
            if i in gi:
                tensors.append(paddle.to_tensor(
                    flat_list[k].reshape(spec.shape)))
                k += 1
            else:
                tensors.append(paddle.to_tensor(x))
        out = _call_case(case, tensors)
        outs = out if isinstance(out, (tuple, list)) else [out]
        tot = 0.0
        for o in outs:
            if hasattr(o, "_data") and np.issubdtype(
                    np.asarray(o._data).dtype, np.floating):
                tot += float(np.asarray(o.sum()._data))
        return tot

    # analytic via tape
    tensors = []
    grad_tensors = []
    for i, (spec, x) in enumerate(zip(case.inputs, np_inputs)):
        t = paddle.to_tensor(x, stop_gradient=(i not in gi))
        tensors.append(t)
        if i in gi:
            grad_tensors.append(t)
    out = _call_case(case, tensors)
    outs = out if isinstance(out, (tuple, list)) else [out]
    loss = None
    for o in outs:
        if hasattr(o, "_data") and np.issubdtype(
                np.asarray(o._data).dtype, np.floating):
            s = o.sum()
            loss = s if loss is None else loss + s
    grads = paddle.grad(loss, grad_tensors, allow_unused=True)

    # numeric via central differences on a sampled coordinate subset
    # (op_test.py checks the full Jacobian on CUDA; eager CPU would take
    # ~1h over the table, so each input checks <= MAX_COORDS random
    # coordinates — the STE/transpose/reduction bugs this hunts are not
    # coordinate-local, so sampling loses no detection power in practice)
    MAX_COORDS = 6
    eps = 1e-3
    coord_rng = np.random.RandomState(zlib.crc32(case.id.encode()))
    flats = [np_inputs[i].ravel().astype("float64") for i in gi]
    for which, i in enumerate(gi):
        analytic = grads[which]
        analytic = (np.zeros(case.inputs[i].shape, "float64")
                    if analytic is None
                    else np.asarray(analytic._data, "float64")).ravel()
        n = flats[which].size
        coords = (np.arange(n) if n <= MAX_COORDS else
                  coord_rng.choice(n, MAX_COORDS, replace=False))
        for j in coords:
            bumped = [f.copy() for f in flats]
            bumped[which][j] += eps
            up = fwd([b.astype("float32") for b in bumped])
            bumped[which][j] -= 2 * eps
            dn = fwd([b.astype("float32") for b in bumped])
            numeric = (up - dn) / (2 * eps)
            scale = max(1.0, abs(numeric), abs(analytic[j]))
            assert abs(analytic[j] - numeric) / scale <= case.gtol, (
                f"{case.id} input#{i} coord {j}: analytic {analytic[j]:.6g} "
                f"vs numeric {numeric:.6g}")


# --------------------------------------------------------------------------
# coverage gate
# --------------------------------------------------------------------------

COVERED_MODULES = [
    "paddle_tpu.tensor.creation", "paddle_tpu.tensor.math",
    "paddle_tpu.tensor.manipulation", "paddle_tpu.tensor.logic",
    "paddle_tpu.tensor.linalg", "paddle_tpu.tensor.search",
    "paddle_tpu.tensor.stat", "paddle_tpu.tensor.random",
    "paddle_tpu.tensor.einsum",
]


def test_every_public_op_has_a_case_or_waiver():
    case_names = set()
    for c in CASES:
        case_names.add(c.path.split(".")[-1])
    missing = []
    for modname in COVERED_MODULES:
        mod = __import__(modname, fromlist=["x"])
        for n in dir(mod):
            if n.startswith("_"):
                continue
            f = getattr(mod, n)
            if not callable(f) or inspect.isclass(f):
                continue
            if not getattr(f, "__module__", "").startswith("paddle_tpu"):
                continue
            if n not in case_names and n not in WAIVERS:
                missing.append(f"{modname}.{n}")
    assert not missing, (
        "ops without an oracle case or waiver (add a Case or a reasoned "
        f"waiver): {missing}")


F_WAIVERS = {
    # tested in dedicated suites (conv/pool/norm/attention/vision files)
    "conv1d": "test_nn_layers conv suite", "conv2d": "test_nn_layers",
    "conv3d": "test_nn_layers", "conv1d_transpose": "test_nn_layers",
    "conv2d_transpose": "test_nn_layers", "conv3d_transpose": "test_nn_layers",
    "avg_pool1d": "test_nn_layers pooling", "avg_pool2d": "test_nn_layers",
    "avg_pool3d": "test_nn_layers", "max_pool1d": "test_nn_layers",
    "max_pool2d": "test_nn_layers", "max_pool3d": "test_nn_layers",
    "adaptive_avg_pool1d": "test_nn_layers", "adaptive_avg_pool2d": "test_nn_layers",
    "adaptive_avg_pool3d": "test_nn_layers", "adaptive_max_pool1d": "test_nn_layers",
    "adaptive_max_pool2d": "test_nn_layers", "adaptive_max_pool3d": "test_nn_layers",
    "max_unpool2d": "test_nn_extras", "batch_norm": "test_nn_layers norm suite",
    "layer_norm": "test_nn_layers", "instance_norm": "test_nn_layers",
    "group_norm": "test_nn_layers",
    "scaled_dot_product_attention": "test_attention parity suite",
    "sparse_attention": "test_attention (masked path)",
    "interpolate": "test_nn_extras", "upsample": "test_nn_extras",
    "fold": "test_nn_extras", "unfold": "test_nn_extras",
    "pixel_unshuffle": "inverse of pixel_shuffle (tested together)",
    "margin_cross_entropy": "test_distributed (class-parallel path)",
    "class_center_sample": "test_distributed",
    "hsigmoid_loss": "test_nn_extras",
    "softmax_with_cross_entropy": "alias of cross_entropy (covered)",
    "gather_tree": "test_incubate_utils beam-search suite",
    "gumbel_softmax": "statistical (random)",
    "dropout": "p>0 statistical; p=0 identity covered above",
    "dropout2d": "statistical (random)", "dropout3d": "statistical (random)",
    "alpha_dropout": "statistical (random)", "rrelu": "statistical (random)",
    "embedding": "covered as F.embedding case",
    "zeropad2d": "thin wrapper over pad (covered)",
    "npu_identity": "compat no-op shim",
    "sequence_mask": "covered as case", "one_hot": "covered as case",
    # in-place aliases
    "elu_": "in-place alias", "relu_": "in-place alias",
    "softmax_": "in-place alias", "tanh_": "in-place alias",
    "apply": "dispatch plumbing",
}


def test_every_functional_op_has_a_case_or_waiver():
    case_names = {c.path.split(".")[-1] for c in CASES if
                  c.path.startswith("F.")}
    missing = []
    for n in dir(F):
        if n.startswith("_"):
            continue
        f = getattr(F, n)
        if not callable(f) or inspect.isclass(f):
            continue
        if n not in case_names and n not in F_WAIVERS:
            missing.append(n)
    assert not missing, (
        "functional ops without an oracle case or waiver: " + str(missing))




# --------------------------------------------------------------------------
# decomposition reconstruction properties (sign/pivot-ambiguous ops the
# direct-compare harness waives; ≙ reference test_qr_op/test_eig_op checks)
# --------------------------------------------------------------------------

class TestDecompositionProperties:
    def _a(self, n=5, m=4, seed=7):
        return np.random.RandomState(seed).randn(n, m).astype("float32")

    def test_qr_reconstructs_and_orthonormal(self):
        a = self._a()
        q, r = _to_np(paddle.linalg.qr(paddle.to_tensor(a)))
        np.testing.assert_allclose(q @ r, a, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(q.T @ q, np.eye(q.shape[1]),
                                   rtol=1e-4, atol=1e-4)
        assert np.allclose(r, np.triu(r))

    def test_eigh_reconstructs(self):
        a = self._a(4, 4)
        sym = (a + a.T) / 2
        w, v = _to_np(paddle.linalg.eigh(paddle.to_tensor(sym)))
        np.testing.assert_allclose(sym @ v, v @ np.diag(w),
                                   rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(np.sort(w), np.linalg.eigvalsh(sym),
                                   rtol=1e-4, atol=1e-4)

    def test_eig_eigvals_match_numpy_sorted(self):
        a = self._a(4, 4)
        w, v = _to_np(paddle.linalg.eig(paddle.to_tensor(a)))
        wv, = _to_np(paddle.linalg.eigvals(paddle.to_tensor(a)))
        ref = np.linalg.eigvals(a)
        key = lambda z: np.sort_complex(z)
        np.testing.assert_allclose(key(w), key(ref), rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(key(wv), key(ref), rtol=1e-3, atol=1e-3)
        # right-eigenvector property A v = w v
        np.testing.assert_allclose(a.astype(v.dtype) @ v, v * w[None, :],
                                   rtol=1e-2, atol=1e-2)

    def test_lu_reconstructs(self):
        a = self._a(4, 4)
        lu_packed, piv = _to_np(paddle.linalg.lu(paddle.to_tensor(a)))
        piv = piv - 1  # paddle pivots are 1-based
        L = np.tril(lu_packed, -1) + np.eye(4, dtype=lu_packed.dtype)
        U = np.triu(lu_packed)
        # apply recorded row swaps to a copy of A (LAPACK ipiv convention)
        pa = a.copy()
        for i, p in enumerate(piv):
            pa[[i, p]] = pa[[p, i]]
        np.testing.assert_allclose(L @ U, pa, rtol=1e-4, atol=1e-4)

    def test_lstsq_solution_is_optimal(self):
        a, b = self._a(6, 3), self._a(6, 2, seed=8)
        sol = _to_np(paddle.linalg.lstsq(paddle.to_tensor(a),
                                         paddle.to_tensor(b)))[0]
        # normal equations: A^T (A x - b) = 0 at the least-squares optimum
        np.testing.assert_allclose(a.T @ (a @ sol - b),
                                   np.zeros((3, 2)), atol=1e-3)
        np.testing.assert_allclose(sol, np.linalg.lstsq(a, b, rcond=None)[0],
                                   rtol=1e-3, atol=1e-3)


def test_einsum_equation_battery():
    """Einsum over the reference test_einsum_op.py equation families."""
    r = np.random.RandomState(11)
    a2 = r.randn(3, 4).astype("float32")
    b2 = r.randn(4, 5).astype("float32")
    a3 = r.randn(2, 3, 4).astype("float32")
    b3 = r.randn(2, 4, 5).astype("float32")
    v = r.randn(4).astype("float32")
    sq = r.randn(4, 4).astype("float32")
    cases = [
        ("ij,jk->ik", (a2, b2)),
        ("bij,bjk->bik", (a3, b3)),
        ("ij->ji", (a2,)),
        ("ii->", (sq,)),            # trace
        ("ii->i", (sq,)),           # diagonal
        ("ij->", (a2,)),            # total sum
        ("ij->j", (a2,)),           # column sum
        ("i,i->", (v, v)),          # dot
        ("i,j->ij", (v, v)),        # outer
        ("ij,j->i", (a2, v)),       # matvec
        ("bij,bik->bjk", (a3, r.randn(2, 3, 6).astype("float32"))),
    ]
    for eq, args in cases:
        got = _to_np(paddle.einsum(eq, *[paddle.to_tensor(x) for x in args]))[0]
        want = np.einsum(eq, *args)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5,
                                   err_msg=eq)
