"""The driver's gating artifact: every bench config's child path must run
and emit valid JSON on the CPU backend (rc=1 here was the round-1 red
BENCH)."""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_all_configs_cpu_child():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["_PADDLE_TPU_BENCH_CHILD"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"), "--config", "all"],
        capture_output=True, text=True, env=env, timeout=900, cwd=ROOT)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip().startswith("{")]
    recs = [json.loads(l) for l in lines]
    names = {r["metric"] for r in recs}
    assert len(recs) >= 6, names  # gpt2s, gpt_long, bert, ernie, resnet, lenet
    for r in recs:
        assert r["value"] is not None and r["value"] > 0, r
        assert r["backend"] == "cpu"


def test_probe_failure_emits_skipped_not_error(monkeypatch, capsys):
    """An unhealthy backend is NOT a benchmark failure: the parent emits
    one ``unit: "skipped"`` record per config carrying the probe tail, so
    the perf trajectory stays parseable (an "error" record here read as a
    code regression every infra-dead round — BENCH_r05)."""
    import json as _json

    import bench
    monkeypatch.setenv("PADDLE_TPU_BENCH_PROBE_ATTEMPTS", "1")
    monkeypatch.setenv("PADDLE_TPU_BENCH_PROBE_BACKOFF", "0")
    monkeypatch.setattr(bench, "_probe_backend",
                        lambda timeout=0.0: (False, 1, "probe boom tail"))
    rc = bench._parent(["gpt2s", "gpt_serving"], attempts=2, timeout=5)
    assert rc == 0
    recs = [_json.loads(ln) for ln in capsys.readouterr().out.splitlines()
            if ln.strip().startswith("{")]
    assert len(recs) == 2
    for r in recs:
        assert r["unit"] == "skipped" and r["value"] is None
        assert "error" not in r
        [probe] = r["skipped"]["probe"]
        assert "probe boom tail" in probe["tail"]


def test_analytic_flops_matches_6n_approximation():
    """_transformer_train_flops ≈ 6·N·tokens + attention term for gpt2s
    (Megatron/PaLM convention); guards the MFU denominator's honesty
    (VERDICT r2: XLA cost analysis undercounted scan models)."""
    import bench
    B, L = 16, 1024
    H, I, V, n = 768, 3072, 50304, 12
    got = bench._transformer_train_flops(B, L, n, H, I, V)
    # parameter count of the matmul path (QKVO 4H^2 + MLP 2HI per layer,
    # plus the tied head HV)
    N = n * (4 * H * H + 2 * H * I) + H * V
    attn = 3 * B * L * n * 4 * L * H          # train (3x) QK^T+PV term
    approx = 6 * N * B * L + attn
    assert abs(got - approx) / approx < 0.01, (got, approx)
    # MoE top-2 doubles only the expert-MLP term
    moe = bench._transformer_train_flops(B, L, n, H, I, V, moe_topk=2)
    assert moe - got == 3 * B * L * n * 4 * H * I


def test_probe_hard_timeout_kills_and_records_real_rc(monkeypatch, tmp_path):
    """The hung-probe leak fix (HEALTH.log `rc=inflight ... [probe left
    running]`): a probe past its deadline is killed — whole process group,
    SIGKILL escalation — and the log records a REAL rc, not a leak."""
    import time as _time

    import bench
    log = tmp_path / "health.log"
    monkeypatch.setenv("PADDLE_TPU_BENCH_HEALTH_LOG", str(log))
    monkeypatch.setattr(bench, "_PROBE_SRC", "import time\ntime.sleep(60)\n")
    t0 = _time.time()
    healthy, rc, _out = bench._probe_backend(timeout=1.5)
    wall = _time.time() - t0
    assert not healthy
    assert isinstance(rc, int) and rc < 0       # died on a signal
    assert wall < 30                            # bounded, not a 60s wait
    line = log.read_text()
    assert "rc=-" in line and "probe killed at" in line
    assert "inflight" not in line and "left running" not in line


def test_probe_healthy_fast_path(monkeypatch, tmp_path):
    import bench
    log = tmp_path / "health.log"
    monkeypatch.setenv("PADDLE_TPU_BENCH_HEALTH_LOG", str(log))
    monkeypatch.setattr(
        bench, "_PROBE_SRC",
        "print('COMPUTE_HEALTHY devices=1 dial=0.0s compute=0.0s v=1.0')")
    healthy, rc, out = bench._probe_backend(timeout=60)
    assert healthy and rc == 0 and "COMPUTE_HEALTHY" in out
    assert "ok COMPUTE_HEALTHY" in log.read_text()
