"""AMP tests (reference: unittests test_amp_* family)."""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.amp as amp
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_autocast_white_black():
    lin = nn.Linear(8, 4)
    x = paddle.randn([2, 8])
    with amp.auto_cast(dtype="bfloat16"):
        assert lin(x).dtype == jnp.bfloat16
        assert paddle.matmul(x, paddle.randn([8, 2])).dtype == jnp.bfloat16
    assert lin(x).dtype == jnp.float32


def test_custom_lists():
    x = paddle.randn([2, 2])
    with amp.auto_cast(custom_black_list={"matmul"}):
        assert paddle.matmul(x, x).dtype == jnp.float32
    with amp.auto_cast(custom_white_list={"softmax"}):
        out = F.softmax(paddle.randn([2, 4]).astype("bfloat16"))
        assert out.dtype == jnp.bfloat16


def test_backward_replays_recorded_state():
    # record outside autocast, backward inside — must stay fp32
    x = paddle.randn([2, 3])
    x.stop_gradient = False
    y = F.linear(x, paddle.randn([3, 3]))
    with amp.auto_cast():
        y.sum().backward()
    assert x.grad.dtype == jnp.float32
    # record inside autocast, backward outside — replay in bf16
    a = paddle.randn([2, 2])
    a.stop_gradient = False
    with amp.auto_cast():
        z = paddle.matmul(a, paddle.randn([2, 2]))
    z.sum().backward()
    assert a.grad is not None


def test_grad_scaler_skip_on_inf():
    lin = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(0.1, parameters=lin.parameters())
    scaler = amp.GradScaler(init_loss_scaling=1024.0,
                            decr_every_n_nan_or_inf=1)
    lin.weight._grad = jnp.full_like(lin.weight._data, jnp.inf)
    lin.bias._grad = jnp.zeros_like(lin.bias._data)
    w0 = lin.weight.numpy().copy()
    scaler.step(opt)
    scaler.update()
    np.testing.assert_allclose(lin.weight.numpy(), w0)
    assert scaler.get_loss_scaling() == 512.0


def test_no_double_unscale():
    from paddle_tpu.nn.utils import clip_grad_norm_
    lin = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(1.0, parameters=lin.parameters())
    scaler = amp.GradScaler(init_loss_scaling=1024.0)
    loss = lin(paddle.ones([1, 4])).sum()
    scaler.scale(loss).backward()
    scaler.unscale_(opt)
    g = lin.bias.grad.numpy().copy()
    np.testing.assert_allclose(g, [1.0, 1.0])
    clip_grad_norm_(lin.parameters(), 1e9)
    scaler.step(opt)  # must NOT unscale again
    scaler.update()
    assert scaler._already_unscaled is False


def test_functional_scaler_under_jit():
    import jax
    scaler = amp.GradScaler(init_loss_scaling=256.0, decr_every_n_nan_or_inf=1)
    state = scaler.init_state()
    good = {"w": jnp.ones((2,)) * 512.0}
    u, fi, st = jax.jit(scaler.functional_update)(state, good)
    assert not bool(fi)
    np.testing.assert_allclose(np.asarray(u["w"]), 2.0)
    bad = {"w": jnp.array([jnp.inf, 1.0])}
    u, fi, st = jax.jit(scaler.functional_update)(state, bad)
    assert bool(fi)
    assert float(st["scale"]) == 128.0


def test_decorate_o2():
    net = nn.Sequential(nn.Linear(4, 4), nn.LayerNorm(4))
    amp.decorate(net, level="O2", dtype="bfloat16")
    assert net[0].weight.dtype == jnp.bfloat16
    assert net[1].weight.dtype == jnp.float32  # norms stay fp32


class TestHapiAmpConfigs:
    def test_prepare_amp_configs_bakes_bf16(self):
        """prepare(amp_configs='O1') must bake bf16 casts into the compiled
        step (jax.jit traces lazily — regression for the wrap-construction
        bug where the context closed before tracing)."""
        import numpy as np
        import jax
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(16, 8), nn.ReLU(), nn.Linear(8, 2))
        model = paddle.Model(net)
        model.prepare(paddle.optimizer.Adam(1e-2, parameters=net.parameters()),
                      paddle.nn.CrossEntropyLoss(), amp_configs="O1")
        model._ensure_train_step()
        rng = np.random.RandomState(0)
        X = rng.standard_normal((8, 16)).astype("float32")
        y = (X[:, 0] > 0).astype("int64")
        hlo = model._train_step.lower(
            model._state, jax.random.key(0), np.float32(1e-2),
            [X], [y]).as_text()
        assert "bf16" in hlo

        plain = paddle.Model(nn.Sequential(nn.Linear(16, 2)))
        plain.prepare(paddle.optimizer.Adam(1e-2,
                                            parameters=plain.network.parameters()),
                      paddle.nn.CrossEntropyLoss())
        plain._ensure_train_step()
        hlo2 = plain._train_step.lower(
            plain._state, jax.random.key(0), np.float32(1e-2),
            [X], [y]).as_text()
        assert "bf16" not in hlo2  # no amp → no bf16

    def test_amp_configs_O0_disables(self):
        import numpy as np
        import jax
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(16, 2))
        model = paddle.Model(net)
        model.prepare(paddle.optimizer.SGD(1e-2, parameters=net.parameters()),
                      paddle.nn.CrossEntropyLoss(), amp_configs="O0")
        model._ensure_train_step()
        rng = np.random.RandomState(0)
        X = rng.standard_normal((8, 16)).astype("float32")
        y = (X[:, 0] > 0).astype("int64")
        hlo = model._train_step.lower(model._state, jax.random.key(0),
                                      np.float32(1e-2), [X], [y]).as_text()
        assert "bf16" not in hlo  # O0 = pure fp32, AMP must stay off

    def test_amp_configs_O2_casts_params(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(16, 8), nn.LayerNorm(8), nn.Linear(8, 2))
        model = paddle.Model(net)
        model.prepare(paddle.optimizer.Adam(1e-2, parameters=net.parameters()),
                      paddle.nn.CrossEntropyLoss(), amp_configs={"level": "O2"})
        model._ensure_train_step()
        import jax.numpy as jnp
        # linear weights cast to bf16; LayerNorm stays fp32 (reference O2)
        assert model._state["params"]["0.weight"].dtype == jnp.bfloat16
        assert model._state["params"]["1.weight"].dtype == jnp.float32
        # fp32 master weights + fp32 moments ride the optimizer slots
        slots = model._state["opt"]["slots"]["0.weight"]
        assert slots["master"].dtype == jnp.float32
        assert slots["moment1"].dtype == jnp.float32

    def test_amp_configs_accum_path_bakes_bf16(self):
        import numpy as np
        import jax
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(16, 2))
        model = paddle.Model(net)
        model.prepare(paddle.optimizer.SGD(1e-2, parameters=net.parameters()),
                      paddle.nn.CrossEntropyLoss(), amp_configs="O1")
        model._accum_batches = 2
        model._ensure_train_step()
        rng = np.random.RandomState(0)
        X = rng.standard_normal((8, 16)).astype("float32")
        y = (X[:, 0] > 0).astype("int64")
        hlo = model._train_step.lower(model._state, jax.random.key(0),
                                      np.float32(1e-2), [X], [y]).as_text()
        assert "bf16" in hlo

    def test_fp16_scaler_skips_on_inf_and_decays(self):
        """In-step dynamic loss scaling: a non-finite grad skips the update
        and halves the scale (check_finite_and_unscale + update_loss_scaling
        semantics)."""
        import numpy as np
        import jax
        import jax.numpy as jnp
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.jit.functional import make_train_step

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 2))
        opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())

        step, state = make_train_step(
            net, paddle.nn.CrossEntropyLoss(), opt,
            scaler_cfg={"init_loss_scaling": 8.0})
        X = np.random.RandomState(0).standard_normal((4, 4)).astype("float32")
        y = np.array([0, 1, 0, 1])
        key = jax.random.key(0)
        s1, _ = step(state, key, np.float32(0.1), [X], [y])
        w_after_1 = np.asarray(s1["params"]["0.weight"])
        assert float(s1["scaler"]["scale"]) == 8.0
        X_inf = X.copy()
        X_inf[0, 0] = np.inf  # data-driven non-finite grads
        s2, _ = step(s1, key, np.float32(0.1), [X_inf], [y])
        # inf loss → grads non-finite → update skipped, scale halved
        np.testing.assert_array_equal(np.asarray(s2["params"]["0.weight"]),
                                      w_after_1)
        assert float(s2["scaler"]["scale"]) == 4.0
