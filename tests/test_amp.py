"""AMP tests (reference: unittests test_amp_* family)."""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.amp as amp
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_autocast_white_black():
    lin = nn.Linear(8, 4)
    x = paddle.randn([2, 8])
    with amp.auto_cast(dtype="bfloat16"):
        assert lin(x).dtype == jnp.bfloat16
        assert paddle.matmul(x, paddle.randn([8, 2])).dtype == jnp.bfloat16
    assert lin(x).dtype == jnp.float32


def test_custom_lists():
    x = paddle.randn([2, 2])
    with amp.auto_cast(custom_black_list={"matmul"}):
        assert paddle.matmul(x, x).dtype == jnp.float32
    with amp.auto_cast(custom_white_list={"softmax"}):
        out = F.softmax(paddle.randn([2, 4]).astype("bfloat16"))
        assert out.dtype == jnp.bfloat16


def test_backward_replays_recorded_state():
    # record outside autocast, backward inside — must stay fp32
    x = paddle.randn([2, 3])
    x.stop_gradient = False
    y = F.linear(x, paddle.randn([3, 3]))
    with amp.auto_cast():
        y.sum().backward()
    assert x.grad.dtype == jnp.float32
    # record inside autocast, backward outside — replay in bf16
    a = paddle.randn([2, 2])
    a.stop_gradient = False
    with amp.auto_cast():
        z = paddle.matmul(a, paddle.randn([2, 2]))
    z.sum().backward()
    assert a.grad is not None


def test_grad_scaler_skip_on_inf():
    lin = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(0.1, parameters=lin.parameters())
    scaler = amp.GradScaler(init_loss_scaling=1024.0,
                            decr_every_n_nan_or_inf=1)
    lin.weight._grad = jnp.full_like(lin.weight._data, jnp.inf)
    lin.bias._grad = jnp.zeros_like(lin.bias._data)
    w0 = lin.weight.numpy().copy()
    scaler.step(opt)
    scaler.update()
    np.testing.assert_allclose(lin.weight.numpy(), w0)
    assert scaler.get_loss_scaling() == 512.0


def test_no_double_unscale():
    from paddle_tpu.nn.utils import clip_grad_norm_
    lin = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(1.0, parameters=lin.parameters())
    scaler = amp.GradScaler(init_loss_scaling=1024.0)
    loss = lin(paddle.ones([1, 4])).sum()
    scaler.scale(loss).backward()
    scaler.unscale_(opt)
    g = lin.bias.grad.numpy().copy()
    np.testing.assert_allclose(g, [1.0, 1.0])
    clip_grad_norm_(lin.parameters(), 1e9)
    scaler.step(opt)  # must NOT unscale again
    scaler.update()
    assert scaler._already_unscaled is False


def test_functional_scaler_under_jit():
    import jax
    scaler = amp.GradScaler(init_loss_scaling=256.0, decr_every_n_nan_or_inf=1)
    state = scaler.init_state()
    good = {"w": jnp.ones((2,)) * 512.0}
    u, fi, st = jax.jit(scaler.functional_update)(state, good)
    assert not bool(fi)
    np.testing.assert_allclose(np.asarray(u["w"]), 2.0)
    bad = {"w": jnp.array([jnp.inf, 1.0])}
    u, fi, st = jax.jit(scaler.functional_update)(state, bad)
    assert bool(fi)
    assert float(st["scale"]) == 128.0


def test_decorate_o2():
    net = nn.Sequential(nn.Linear(4, 4), nn.LayerNorm(4))
    amp.decorate(net, level="O2", dtype="bfloat16")
    assert net[0].weight.dtype == jnp.bfloat16
    assert net[1].weight.dtype == jnp.float32  # norms stay fp32
