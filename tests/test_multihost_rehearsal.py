"""Multi-host rehearsal on localhost (VERDICT r3 item 6): the WHOLE elastic
chain end-to-end in real separate processes —

  ``python -m paddle_tpu.distributed.launch --elastic_store tcp://...``
  → launcher hosts the native C++ TCP KV store (csrc/kv_store.cpp)
  → 2 worker processes rendezvous through it (ElasticManager heartbeats)
  → ``init_parallel_env`` brings up jax.distributed (Gloo CPU collectives)
  → a REAL dp-sharded train step (GSPMD mean-grad = cross-process psum)
  → dp-sharded checkpoint (distributed/checkpoint.py, each process writes
    only its shards)
  → rank 1 SIGKILLs itself mid-run (the elastic fault)
  → launcher --elastic_level 1 restarts the pod
  → both workers resume from the checkpoint and finish.

Reference flows: fleet/launch.py + launch_utils.py watch_local_trainers
(launcher), fleet/elastic/manager.py (membership/restart), distributed/
parallel.py init_parallel_env:71 (env contract), all exercised here against
the framework's own no-etcd store.

Pieces are unit-tested separately in test_store.py / test_launch_elastic.py /
test_checkpoint.py; this file is the integration proof that they compose.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

TRAINER = textwrap.dedent("""
    import os, signal
    import numpy as np
    import jax, jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.distributed import init_parallel_env, get_rank
    from paddle_tpu.distributed import checkpoint as dckpt
    from paddle_tpu.distributed.fleet.elastic import ElasticManager

    STORE = os.environ["PADDLE_ELASTIC_STORE"]   # exported by the launcher
    CKPT = os.environ["REHEARSAL_CKPT"]
    FLAG = os.environ["REHEARSAL_FLAG"]     # exists => the fault already fired
    TOTAL_STEPS = 6

    init_parallel_env()                     # jax.distributed from PADDLE_* env
    rank = get_rank()
    member = ElasticManager(STORE, rank=rank, heartbeat_interval=0.2,
                            lease_ttl=10.0)
    member.register()
    assert jax.process_count() == 2, jax.process_count()

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    repl = NamedSharding(mesh, P())
    row_sharded = NamedSharding(mesh, P("dp", None))
    ndev = jax.device_count()

    rng = np.random.RandomState(0)
    X = rng.randn(ndev * 2, 4).astype(np.float32)
    Y = X @ np.arange(8, dtype=np.float32).reshape(4, 2)
    rows = X.shape[0] // jax.process_count()
    x = jax.make_array_from_process_local_data(
        row_sharded, X[rank * rows:(rank + 1) * rows], global_shape=X.shape)
    y = jax.make_array_from_process_local_data(
        row_sharded, Y[rank * rows:(rank + 1) * rows], global_shape=Y.shape)

    w0 = jax.device_put(np.zeros((4, 2), np.float32), row_sharded)

    @jax.jit
    def train_step(w, x, y):
        def loss_fn(w):
            return jnp.mean((x @ w - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(w)
        return w - 0.05 * g, loss           # GSPMD inserts the grad psum

    start, w = 0, w0
    if os.path.isdir(CKPT) and os.listdir(CKPT):
        state = dckpt.load(CKPT, target={"w": w0, "step": 0},
                           shardings={"w": row_sharded, "step": None})
        start, w = int(state["step"]), state["w"]

    loss = None
    for step in range(start, TOTAL_STEPS):
        w, loss = train_step(w, x, y)
        dckpt.save({"w": w, "step": step + 1}, CKPT).wait()
        if rank == 1 and step == 2 and not os.path.exists(FLAG):
            open(FLAG, "w").close()         # flag first: kill exactly once
            os.kill(os.getpid(), signal.SIGKILL)

    member.stop()
    if rank == 0:
        print(f"REHEARSAL_DONE resumed_from={start} "
              f"loss={float(loss):.6f}", flush=True)
""")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.skipif(os.environ.get("PADDLE_TPU_SKIP_SUBPROC") == "1",
                    reason="subprocess tests disabled")
def test_launch_tcp_store_fault_restart_resume(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(TRAINER)
    store_port, master_port = _free_port(), _free_port()

    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    env["REHEARSAL_CKPT"] = str(tmp_path / "ckpt")
    env["REHEARSAL_FLAG"] = str(tmp_path / "fault_fired")

    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--devices", "cpu", "--nproc_per_node", "2",
         "--master", f"127.0.0.1:{master_port}",
         "--elastic_level", "1", "--max_restarts", "2",
         "--elastic_store", f"tcp://127.0.0.1:{store_port}",
         "--log_dir", str(tmp_path / "log"), str(script)],
        capture_output=True, text=True, timeout=600, env=env,
        cwd="/root/repo")

    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    # the fault actually fired, the pod restarted, and the relaunched run
    # resumed from the step-3 checkpoint rather than from scratch
    assert os.path.exists(env["REHEARSAL_FLAG"])
    assert "elastic restart 1/" in r.stderr, r.stderr[-2000:]
    assert "REHEARSAL_DONE resumed_from=3" in r.stdout, r.stdout[-2000:]
    # training really progressed: 6 SGD steps on y = x @ w* from w=0 must cut
    # the loss well below the step-0 value (~70 for this fixed seed; 6 steps
    # at lr 0.05 land ~40)
    loss = float(r.stdout.split("loss=")[1].split()[0])
    assert 0.0 < loss < 50.0, loss
