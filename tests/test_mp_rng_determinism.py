"""Model-parallel RNG seeding must be a pure function of (seed, mp rank).

The pre-tpulint code fell back to ``random.randint(0, 100000)`` when no
seed was given — every host drew a DIFFERENT global seed, so the
"identical across ranks" contract of the global stream silently broke the
moment a job relied on the default (the exact replica-divergence hazard
tpulint's ``unseeded-nondeterminism`` rule exists for).  Now the default
derives from ``FLAGS_seed``: same flags ⇒ same tracker state on every
host, no process-global randomness involved."""

import random as pyrandom

import pytest

import paddle_tpu
from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.random import (
    MODEL_PARALLEL_RNG, get_rng_state_tracker, model_parallel_random_seed)


@pytest.fixture(autouse=True)
def _restore_global_rng_state():
    """model_parallel_random_seed reseeds the PROCESS-GLOBAL tracker and
    FLAGS_seed; put both back so this module can't leak state downstream."""
    yield
    get_rng_state_tracker().seeds.pop(MODEL_PARALLEL_RNG, None)
    paddle_tpu.seed(0)  # suite default: FLAGS_seed=0, fresh streams


def _tracker_seeds_on_host(monkeypatch, rank, seed=None):
    """Simulate one host: pin the trainer rank, (re)seed, snapshot."""
    monkeypatch.setenv("PADDLE_TRAINER_ID", str(rank))
    model_parallel_random_seed(seed)
    return dict(get_rng_state_tracker().seeds)


def test_same_seed_agrees_across_hosts(monkeypatch):
    a = _tracker_seeds_on_host(monkeypatch, rank=0, seed=1234)
    b = _tracker_seeds_on_host(monkeypatch, rank=0, seed=1234)
    assert a == b, "same (seed, rank) must rebuild the identical tracker"
    assert a[MODEL_PARALLEL_RNG] == 1234 + 1024 + 0


def test_local_stream_differs_per_rank_deterministically(monkeypatch):
    r0 = _tracker_seeds_on_host(monkeypatch, rank=0, seed=1234)
    r1 = _tracker_seeds_on_host(monkeypatch, rank=1, seed=1234)
    # dropout inside sharded layers must differ across TP ranks ...
    assert r0[MODEL_PARALLEL_RNG] != r1[MODEL_PARALLEL_RNG]
    # ... but by the documented deterministic offset, not by luck
    assert r1[MODEL_PARALLEL_RNG] - r0[MODEL_PARALLEL_RNG] == 1

def test_default_seed_is_deterministic_not_process_random(monkeypatch):
    """seed=None derives from FLAGS_seed — never from random.randint."""
    def _boom(*a, **k):
        raise AssertionError("model_parallel_random_seed drew from the "
                             "process-global random module")
    monkeypatch.setattr(pyrandom, "randint", _boom)
    paddle_tpu.set_flags({"FLAGS_seed": 777})
    host_a = _tracker_seeds_on_host(monkeypatch, rank=1, seed=None)
    host_b = _tracker_seeds_on_host(monkeypatch, rank=1, seed=None)
    assert host_a == host_b
    assert host_a[MODEL_PARALLEL_RNG] == 777 + 1024 + 1
