"""Logits processors (repetition penalty, min_new_tokens) vs the HF
transformers oracles — the reference ecosystem's generation_utils knobs
(repetition_penalty / min_length) on our decode stack."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models._decode import apply_repetition_penalty, suppress_eos
from paddle_tpu.models.gpt import GPTConfig, GPTModel


class TestProcessorOracles:
    def test_repetition_penalty_matches_transformers(self):
        from transformers import RepetitionPenaltyLogitsProcessor
        rng = np.random.RandomState(0)
        V, B = 50, 3
        scores = rng.randn(B, V).astype("float32") * 3
        ids = rng.randint(0, V, (B, 7))
        import torch
        oracle = RepetitionPenaltyLogitsProcessor(1.7)(
            torch.tensor(ids), torch.tensor(scores)).numpy()
        presence = np.zeros((B, V), bool)
        np.put_along_axis(presence, ids, True, axis=1)
        got = np.asarray(apply_repetition_penalty(
            jnp.asarray(scores), jnp.asarray(presence), 1.7))
        np.testing.assert_allclose(got, oracle, rtol=1e-6)

    def test_suppress_eos_semantics(self):
        logits = jnp.asarray(np.random.RandomState(1).randn(2, 9), jnp.float32)
        out = np.asarray(suppress_eos(logits, 4, jnp.bool_(True)))
        assert np.isneginf(out[:, 4]).all()
        np.testing.assert_array_equal(np.delete(out, 4, 1),
                                      np.delete(np.asarray(logits), 4, 1))
        out2 = np.asarray(suppress_eos(logits, 4, jnp.bool_(False)))
        np.testing.assert_array_equal(out2, np.asarray(logits))


@pytest.fixture(scope="module")
def model_and_params():
    paddle.seed(23)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=64,
                    compute_dtype="float32")
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    return model, params


class TestGenerateWithProcessors:
    def test_repetition_penalty_breaks_greedy_loops(self, model_and_params):
        """A random-init greedy run collapses into a repeated token; a
        strong repetition penalty must produce all-distinct tokens (each
        emission pushes that token down for the rest of the run)."""
        model, params = model_and_params
        ids = jnp.asarray([[5, 17, 3]], jnp.int32)
        plain = np.asarray(model.generate(params, ids, 10, greedy=True))[0]
        assert len(set(plain.tolist())) < 10      # the loop to break
        pen = np.asarray(model.generate(params, ids, 10, greedy=True,
                                        repetition_penalty=10.0))[0]
        assert len(set(pen.tolist())) == 10
        # prompt tokens are penalized too (seeded presence)
        assert not (set(pen.tolist()) & {5, 17, 3})

    def test_penalty_1_is_exactly_plain_generation(self, model_and_params):
        model, params = model_and_params
        ids = jnp.asarray([[5, 17, 3], [40, 2, 9]], jnp.int32)
        a = np.asarray(model.generate(params, ids, 8, greedy=True))
        b = np.asarray(model.generate(params, ids, 8, greedy=True,
                                      repetition_penalty=1.0))
        np.testing.assert_array_equal(a, b)

    def test_min_new_tokens_suppresses_eos(self, model_and_params):
        """Declare the plain run's dominant token as EOS — it would
        otherwise appear immediately; with min_new_tokens=6 it must not
        appear among the first 6 emissions, and suppression must lapse
        afterwards (the dominant token returns once allowed)."""
        model, params = model_and_params
        ids = jnp.asarray([[5, 17, 3]], jnp.int32)
        plain = np.asarray(model.generate(params, ids, 8, greedy=True))[0]
        eos = int(plain[0])                       # emitted at position 0
        out = np.asarray(model.generate(params, ids, 8, greedy=True,
                                        min_new_tokens=6,
                                        eos_token_id=eos))[0]
        assert eos not in out[:6].tolist()
        # suppression visibly acted: unconstrained greedy emits eos FIRST
        # (not vacuous), and the constrained run had to pick something else
        assert int(out[0]) != eos

    def test_masked_prompts_seed_presence_without_pads(self, model_and_params):
        """Left-padded prompts: the pad token id (0) must NOT be penalized
        via the pad positions — only real prompt tokens are."""
        model, params = model_and_params
        ids = jnp.asarray([[0, 0, 5, 17, 3]], jnp.int32)
        mask = np.asarray([[0, 0, 1, 1, 1]], np.int32)
        unpadded = jnp.asarray([[5, 17, 3]], jnp.int32)
        a = np.asarray(model.generate(params, unpadded, 8, greedy=True,
                                      repetition_penalty=10.0))
        b = np.asarray(model.generate(params, ids, 8, greedy=True,
                                      prompt_mask=mask,
                                      repetition_penalty=10.0))
        np.testing.assert_array_equal(a, b)       # pad rows don't change it

    def test_validation(self, model_and_params):
        model, params = model_and_params
        ids = jnp.asarray([[5]], jnp.int32)
        with pytest.raises(ValueError, match="repetition_penalty"):
            model.generate(params, ids, 4, repetition_penalty=0.0)
        with pytest.raises(ValueError, match="eos_token_id"):
            model.generate(params, ids, 4, min_new_tokens=2)
