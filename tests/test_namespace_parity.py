"""Automated namespace parity vs the reference's static ``__all__`` lists.

Parses /root/reference/python/paddle/*.py with ast (never imports reference
code) and asserts every exported name resolves on the paddle_tpu twin.
Skips when the reference checkout is absent (CI on other machines)."""

import ast
import importlib
import os

import pytest

REF = "/root/reference/python/paddle"

pytestmark = pytest.mark.skipif(not os.path.isdir(REF),
                                reason="reference checkout not mounted")

# (reference module path relative to python/paddle, our module, known waivers)
CASES = [
    ("__init__", "paddle_tpu", set()),
    ("nn/__init__", "paddle_tpu.nn", set()),
    ("nn/functional/__init__", "paddle_tpu.nn.functional", set()),
    ("nn/initializer/__init__", "paddle_tpu.nn.initializer", set()),
    ("optimizer/__init__", "paddle_tpu.optimizer", set()),
    ("distributed/__init__", "paddle_tpu.distributed", set()),
    ("distributed/fleet/__init__", "paddle_tpu.distributed.fleet", set()),
    ("static/__init__", "paddle_tpu.static", set()),
    ("jit/__init__", "paddle_tpu.jit", set()),
    ("amp/__init__", "paddle_tpu.amp", set()),
    ("io/__init__", "paddle_tpu.io", set()),
    ("utils/__init__", "paddle_tpu.utils", set()),
    ("incubate/__init__", "paddle_tpu.incubate", set()),
    ("autograd/__init__", "paddle_tpu.autograd", set()),
    ("device/__init__", "paddle_tpu.device", set()),
    ("fft", "paddle_tpu.fft", set()),
    ("signal", "paddle_tpu.signal", set()),
    ("linalg", "paddle_tpu.tensor.linalg", set()),
    ("vision/ops", "paddle_tpu.vision.ops", set()),
    ("distribution", "paddle_tpu.distribution", set()),
]


def _ref_all(path):
    import warnings
    try:
        with warnings.catch_warnings():
            # the reference's own docstrings contain '\o'-style escapes;
            # their SyntaxWarnings are not our suite's problem
            warnings.simplefilter("ignore", SyntaxWarning)
            tree = ast.parse(open(path).read())
    except (OSError, SyntaxError):
        return None
    names = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, "id", "") == "__all__":
                    names += [e.value for e in node.value.elts
                              if isinstance(e, ast.Constant)]
    return names or None


@pytest.mark.parametrize("ref_rel,ours,waived",
                         CASES, ids=[c[0] for c in CASES])
def test_namespace_complete(ref_rel, ours, waived):
    path = os.path.join(REF, ref_rel + ".py")
    if not os.path.exists(path):
        path = os.path.join(REF, ref_rel, "__init__.py")
    names = _ref_all(path)
    if names is None:
        pytest.skip(f"no static __all__ in {ref_rel}")
    mod = importlib.import_module(ours)
    missing = sorted(n for n in names if not hasattr(mod, n))
    missing = [n for n in missing if n not in waived]
    assert not missing, f"{ours} missing {len(missing)} reference names: {missing}"
