"""tpulint --program stage: whole-program concurrency passes + sanitizer.

Layers, mirroring test_tpulint_gate.py's structure for the per-file stage:

1. the frozen fixture corpus — every bad_* fixture trips EXACTLY its one
   rule and every clean_* twin is silent, so each program rule has a
   CI-exercised true positive and a near-miss;
2. the program model itself (reachability seeds + label propagation,
   guarded-by inference corner cases, inherited-locks fixpoint) over
   scratch trees;
3. the runtime lock sanitizer (order-graph inversions, guarded-container
   violations, annotation harvesting) — the dynamic complement;
4. the CLI: --program JSON schema, stage-aware ratchet, --changed-only,
   and the per-file result cache.

Everything here is stdlib-only — no JAX import, same as the linter.
"""

import json
import pathlib
import subprocess
import sys
import textwrap
import threading

import pytest

from paddle_tpu.analysis import (PROGRAM_RULES, LockSanitizer, Program,
                                 analyze_program)

ROOT = pathlib.Path(__file__).parent.parent
CLI = ROOT / "tools" / "tpulint.py"
FIXTURES = ROOT / "paddle_tpu" / "analysis" / "fixtures" / "program"


def _run(*args, **kw):
    return subprocess.run([sys.executable, str(CLI), *map(str, args)],
                          capture_output=True, text=True, **kw)


def _analyze(path):
    findings, report = analyze_program([path], root=ROOT)
    return findings, report


def _analyze_src(tmp_path, source, name="mod.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return analyze_program([tmp_path], root=tmp_path)


def _build(tmp_path, source, name="mod.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return Program.build([tmp_path], root=tmp_path)


# ----------------------------------------------------------- fixture corpus

@pytest.mark.parametrize("fixture, rule", [
    ("bad_disagg", "guarded-by-race"),
    ("bad_firing", "unguarded-shared-state"),
    ("bad_publish.py", "publish-before-init"),
    ("bad_annotation.py", "bad-guarded-by"),
])
def test_bad_fixture_trips_exactly_its_rule(fixture, rule):
    findings, _ = _analyze(FIXTURES / fixture)
    rules = {f.rule for f in findings}
    assert rules == {rule}, (
        f"{fixture} must trip ONLY {rule}, got: "
        + "\n".join(f.render() for f in findings))


@pytest.mark.parametrize("fixture", [
    "clean_disagg", "clean_firing", "clean_publish.py",
    "clean_annotation.py",
])
def test_clean_twin_is_silent(fixture):
    findings, _ = _analyze(FIXTURES / fixture)
    assert not findings, "\n".join(f.render() for f in findings)


def test_disagg_acceptance_shape():
    """ISSUE acceptance: the race pass must flag the exact post-PR-8
    gateway._disagg reproduction — unlocked iterate of a lock-written
    dict from an http-handler path — naming the guard and the threads."""
    findings, _ = _analyze(FIXTURES / "bad_disagg")
    [f] = findings
    assert f.path.endswith("bad_disagg/gateway_mod.py")
    assert "_jobs" in f.message and "_jobs_lock" in f.message
    assert "http-handler" in f.message


def test_firing_acceptance_shape():
    """ISSUE acceptance: unlocked set churn from a subscriber callback
    against main-path iteration — the pre-PR-11 autoscaler._firing
    shape — with every racing site listed."""
    findings, _ = _analyze(FIXTURES / "bad_firing")
    assert len(findings) == 3          # add + discard + sorted() iterate
    assert all("_firing" in f.message for f in findings)
    assert any("subscriber" in f.message for f in findings)


def test_every_program_rule_has_a_fixture_true_positive():
    findings, _ = _analyze(FIXTURES)
    assert {f.rule for f in findings} == set(PROGRAM_RULES)


# ------------------------------------------------- reachability + seeding

def test_thread_seed_labels(tmp_path):
    _, report = _analyze_src(tmp_path, """\
        import concurrent.futures
        import signal
        import threading

        class Widget:
            def __init__(self, monitor, pool):
                threading.Thread(target=self._spin, daemon=True).start()
                monitor.subscribe(self._on_alert)
                pool.submit(self._crunch)
                signal.signal(signal.SIGTERM, self._on_term)

            def _spin(self): pass
            def _on_alert(self, alert): pass
            def _crunch(self): pass
            def _on_term(self, *a): pass
        """)
    by_target = {row["target"]: row["label"] for row in report.seed_table}
    assert by_target["_spin"] == "thread-target"
    assert by_target["_on_alert"] == "subscriber"
    assert by_target["_crunch"] == "pool-task"
    assert by_target["_on_term"] == "signal-handler"


def test_http_handler_methods_are_entry_points(tmp_path):
    prog = _build(tmp_path, """\
        from http.server import BaseHTTPRequestHandler

        class Routes(BaseHTTPRequestHandler):
            def do_GET(self):
                self._render()
            def _render(self): pass
        """)
    shared = prog.propagate()
    assert "http-handler" in shared["mod.Routes.do_GET"]
    # the label flows through the call graph, not just the entry method
    assert "http-handler" in shared["mod.Routes._render"]


def test_labels_propagate_transitively(tmp_path):
    prog = _build(tmp_path, """\
        import threading

        class Deep:
            def __init__(self):
                threading.Thread(target=self._a).start()
            def _a(self): self._b()
            def _b(self): self._c()
            def _c(self): pass
            def _unreached(self): pass
        """)
    shared = prog.propagate()
    assert "thread-target" in shared["mod.Deep._c"]
    assert "mod.Deep._unreached" not in shared


# ---------------------------------------------------- guarded-by corners

def test_aliased_lock_counts_as_held(tmp_path):
    findings, _ = _analyze_src(tmp_path, """\
        import threading

        class Aliased:
            def __init__(self):
                self._lock = threading.Lock()
                self._jobs = {}
                threading.Thread(target=self._spin).start()
            def _spin(self):
                with self._lock:
                    self._jobs["k"] = 1
            def snapshot(self):
                lk = self._lock
                with lk:
                    return dict(self._jobs)
        """)
    assert not findings, "\n".join(f.render() for f in findings)


def test_multi_item_and_nested_with(tmp_path):
    findings, _ = _analyze_src(tmp_path, """\
        import threading

        class Nested:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._jobs = {}
                threading.Thread(target=self._spin).start()
            def _spin(self):
                with self._a, self._b:
                    self._jobs["k"] = 1
            def snapshot(self):
                with self._a:
                    with self._b:
                        return dict(self._jobs)
        """)
    assert not findings, "\n".join(f.render() for f in findings)


def test_comment_above_annotation_is_recognized(tmp_path):
    prog = _build(tmp_path, """\
        import threading

        class Annotated:
            def __init__(self):
                self._lock = threading.Lock()
                # guarded-by: none (scratch rebuilt per call; the extra
                # comment line here must not break the attachment)
                self._scratch = []
                self._live = {}     # guarded-by: _lock
        """)
    ci = prog.classes["mod.Annotated"]
    assert ci.guarded_by["_scratch"][0] == "none"
    assert ci.guarded_by["_live"][0] == "_lock"


def test_declared_guard_flags_unlocked_threaded_read(tmp_path):
    findings, _ = _analyze_src(tmp_path, """\
        import threading

        class Declared:
            def __init__(self):
                self._lock = threading.Lock()
                self._jobs = {}     # guarded-by: _lock
                threading.Thread(target=self._spin).start()
            def _spin(self):
                for k in self._jobs:     # iterate without the lock
                    pass
            def put(self, k):
                with self._lock:
                    self._jobs[k] = 1
        """)
    [f] = [f for f in findings if f.rule == "guarded-by-race"]
    assert "declared" in f.message and "_spin" in f.message


def test_inherited_locks_suppress_helper_false_positive(tmp_path):
    """A private helper called ONLY with the lock held must not read as
    an unlocked access — the Tracer._append shape the fixpoint exists
    for.  The unlocked-caller twin below must still be flagged."""
    findings, _ = _analyze_src(tmp_path, """\
        import threading

        class Held:
            def __init__(self):
                self._lock = threading.Lock()
                self._rows = []
                threading.Thread(target=self._spin).start()
            def _spin(self):
                with self._lock:
                    self._append(1)
            def put(self, x):
                with self._lock:
                    self._append(x)
            def _append(self, x):
                self._rows.append(x)     # caller provably holds _lock
        """)
    assert not findings, "\n".join(f.render() for f in findings)

    findings, _ = _analyze_src(tmp_path, """\
        import threading

        class Leaky:
            def __init__(self):
                self._lock = threading.Lock()
                self._rows = []
                threading.Thread(target=self._spin).start()
            def _spin(self):
                with self._lock:
                    self._append(1)
            def put(self, x):
                self._append(x)          # one unlocked caller breaks it
            def _append(self, x):
                self._rows.append(x)
        """, name="leaky.py")
    assert any(f.rule in ("guarded-by-race", "unguarded-shared-state")
               for f in findings), "\n".join(f.render() for f in findings)


def test_base_class_declaration_covers_subclass(tmp_path):
    """A # guarded-by: none on the base's init line must silence the
    subclass's mutations too (the Layer/LayerDict shape)."""
    findings, _ = _analyze_src(tmp_path, """\
        import threading

        class Base:
            def __init__(self):
                # guarded-by: none (built on one thread, frozen after)
                self._subs = {}
                threading.Thread(target=self._spin).start()
            def _spin(self):
                for k in self._subs:
                    pass

        class Child(Base):
            def add(self, k, v):
                self._subs[k] = v
        """)
    assert not findings, "\n".join(f.render() for f in findings)


def test_pragma_suppresses_program_finding(tmp_path):
    findings, _ = _analyze_src(tmp_path, """\
        import threading

        class Pragmad:
            def __init__(self):
                self._jobs = {}
                threading.Thread(target=self._spin).start()
            def _spin(self):
                self._jobs["k"] = 1  # tpulint: disable=unguarded-shared-state (test)
            def snapshot(self):
                return dict(self._jobs)  # tpulint: disable=unguarded-shared-state (test)
        """)
    assert not findings, "\n".join(f.render() for f in findings)


# ------------------------------------------------------- runtime sanitizer

def test_sanitizer_records_lock_order_inversion():
    san = LockSanitizer("inversion")
    a = san.wrap(threading.Lock(), "a")
    b = san.wrap(threading.Lock(), "b")
    with a:
        with b:
            pass
    with b:
        with a:                       # reverse order closes the cycle
            pass
    [v] = san.violations()
    assert v["kind"] == "lock-order-inversion"
    assert v["edge"] == "b -> a"
    assert __file__ in v["site"]
    with pytest.raises(AssertionError, match="lock-order inversion"):
        san.assert_clean()


def test_sanitizer_consistent_order_is_clean():
    san = LockSanitizer("ordered")
    a = san.wrap(threading.Lock(), "a")
    b = san.wrap(threading.Lock(), "b")
    for _ in range(3):
        with a:
            with b:
                pass
    san.assert_clean()
    assert ("a", "b") in san.lock_order_edges()


def test_guarded_container_records_unlocked_access():
    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._jobs = {}

    box = Box()
    san = LockSanitizer("guard")
    assert san.guard(box, "_jobs", "_lock")
    with box._lock:
        box._jobs["k"] = 1            # held: clean
    assert isinstance(box._jobs, dict)  # __class__ forwarding
    for _k in box._jobs:              # iterate without the lock: recorded
        pass
    box._jobs.pop("k")                # mutate without the lock: recorded
    kinds = [(v["kind"], v["op"]) for v in san.violations()]
    assert kinds == [("guarded-by", "iterate"), ("guarded-by", "mutate")]
    with pytest.raises(AssertionError, match="guarded-by violation"):
        san.assert_clean()


def test_guard_violation_recorded_not_raised_in_thread():
    """The proxy must RECORD from a second thread, never raise into it —
    raising inside __iter__ would turn a diagnosis into a new crash."""
    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._jobs = {"k": 1}

    box = Box()
    san = LockSanitizer("threaded")
    san.guard(box, "_jobs", "_lock")
    errors = []

    def reader():
        try:
            for _k in box._jobs:
                pass
        except BaseException as e:     # pragma: no cover - the bug case
            errors.append(e)

    t = threading.Thread(target=reader)
    t.start()
    t.join()
    assert not errors
    [v] = san.violations()
    assert v["thread"] == t.name and v["op"] == "iterate"


def test_instrument_guards_harvests_annotations():
    """Statically-declared discipline becomes a runtime assertion with no
    duplicate bookkeeping — trailing AND comment-above forms."""
    class Annotated:
        def __init__(self):
            self._lock = threading.Lock()
            self._live = {}      # guarded-by: _lock
            # guarded-by: _lock
            self._also = []
            self._free = set()   # guarded-by: none (never shared)

    obj = Annotated()
    san = LockSanitizer("harvest")
    wired = san.instrument_guards(obj)
    assert sorted(wired) == [("_also", "_lock"), ("_live", "_lock")]
    with obj._lock:
        obj._live["k"] = 1
        obj._also.append(1)
    san.assert_clean()
    obj._live["k"] = 2               # unlocked: recorded
    assert [v["attr"] for v in san.violations()] == ["Annotated._live"]


def test_instrument_wraps_all_lock_attrs_idempotently():
    class Two:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.RLock()
            self._not_a_lock = 7

    obj = Two()
    san = LockSanitizer("wrap")
    assert sorted(san.instrument(obj)) == ["_a", "_b"]
    assert san.instrument(obj) == []   # second pass: nothing left to wrap
    with obj._a:
        assert obj._a.held_by_current_thread()
    assert not obj._a.held_by_current_thread()


# ------------------------------------------------------------------- CLI

def test_cli_program_json_schema(tmp_path):
    res = _run("--no-baseline", "--json", "--program", "--no-cache",
               FIXTURES / "bad_disagg", cwd=ROOT)
    assert res.returncode == 1
    doc = json.loads(res.stdout)
    assert [f["rule"] for f in doc["findings"]] == ["guarded-by-race"]
    prog = doc["program"]
    assert set(prog) == {"thread_entries", "shared_methods", "guarded_attrs"}
    labels = {row["label"] for row in prog["thread_entries"]}
    assert "http-handler" in labels
    [row] = prog["guarded_attrs"]
    assert row["attr"] == "_jobs" and row["lock"] == "_jobs_lock"


def test_cli_program_ratchet_is_stage_aware(tmp_path):
    """A baseline written WITH --program must not read as stale in a
    per-file-only run (and vice versa) — the two stages share one file."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "a.py").write_text((FIXTURES / "bad_publish.py").read_text())
    baseline = tmp_path / "baseline.json"

    def run(*extra):
        return _run("--root", tmp_path, "--baseline", baseline,
                    "--no-cache", "proj", *extra)

    assert run("--write-baseline", "--program").returncode == 0
    assert run("--program").returncode == 0
    # per-file-only run: frozen program counts are out of scope, not stale
    assert run().returncode == 0
    # burning the program finding down IS stale for a --program run
    (proj / "a.py").write_text("x = 1\n")
    assert run().returncode == 0
    res = run("--program")
    assert res.returncode == 3
    assert "STALE" in res.stderr
    assert run("--write-baseline", "--program").returncode == 0
    assert run("--program").returncode == 0


def test_cli_changed_only_lints_only_git_changed(tmp_path):
    git = ["git", "-C", str(tmp_path)]
    subprocess.run(git + ["init", "-q"], check=True)
    subprocess.run(git + ["-c", "user.email=t@t", "-c", "user.name=t",
                          "commit", "-q", "--allow-empty", "-m", "seed"],
                   check=True)
    proj = tmp_path / "proj"
    proj.mkdir()
    # committed file carries a violation; only the NEW file should be seen
    (proj / "old.py").write_text(
        "try:\n    pass\nexcept Exception:\n    pass\n")
    subprocess.run(git + ["add", "proj/old.py"], check=True)
    subprocess.run(git + ["-c", "user.email=t@t", "-c", "user.name=t",
                          "commit", "-q", "-m", "old"], check=True)
    (proj / "new.py").write_text("x = 1\n")
    res = _run("--root", tmp_path, "--no-baseline", "--no-cache",
               "--changed-only", "--json", "proj")
    assert res.returncode == 0, res.stdout + res.stderr
    doc = json.loads(res.stdout)
    paths = {f["path"] for f in doc["findings"]}
    assert "proj/old.py" not in paths  # unchanged: skipped entirely

    (proj / "new.py").write_text(
        "try:\n    pass\nexcept Exception:\n    pass\n")
    res = _run("--root", tmp_path, "--no-baseline", "--no-cache",
               "--changed-only", "--json", "proj")
    assert res.returncode == 1
    doc = json.loads(res.stdout)
    assert {f["path"] for f in doc["findings"]} == {"proj/new.py"}


def test_cli_cache_round_trip(tmp_path):
    """Second run over an unchanged tree must serve from the memo (same
    findings), and an edit must invalidate just that file."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "a.py").write_text(
        "try:\n    pass\nexcept Exception:\n    pass\n")
    cache = tmp_path / "cache.json"

    def run():
        res = _run("--root", tmp_path, "--no-baseline", "--json",
                   "--cache", cache, "proj")
        return res.returncode, json.loads(res.stdout)["findings"]

    rc1, f1 = run()
    assert rc1 == 1 and cache.exists()
    cached = json.loads(cache.read_text())
    assert "proj/a.py" in cached["files"]
    rc2, f2 = run()
    assert (rc2, f2) == (rc1, f1)      # memo hit: identical verdict
    (proj / "a.py").write_text("x = 1\n")
    rc3, f3 = run()
    assert rc3 == 0 and f3 == []       # stale entry replaced, not reused
