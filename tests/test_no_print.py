"""Library hygiene lint: no ``print()`` in paddle_tpu/ library code.

Library output must flow through ``logging`` (or an explicit callback /
registry) so serving hosts can route, rate-limit, and silence it —
round-6's profiler ``stop_profiler`` print was invisible to log pipelines
and unconditionally noisy in tests.  A frozen allowlist covers the
modules whose printing IS their contract (CLI entry points, console
progress UIs, reference-parity verbose knobs, the ``paddle.static.Print``
op).  Adding a print anywhere else fails this test; removing one from an
allowlisted file requires pruning the list (keeps it honest in both
directions)."""

import ast
import pathlib

PKG = pathlib.Path(__file__).parent.parent / "paddle_tpu"

# Files whose print() calls are their documented job — NOT a dumping
# ground: every entry must be a CLI entry point, console UI, or a
# reference-parity API that prints by contract.
PRINT_ALLOWLIST = {
    "core/tensor.py",                       # FLAGS-gated eager debug echo
    "distributed/fleet/utils/__init__.py",  # fleet log_util console sink
    "distributed/launch.py",                # CLI entry point
    "hapi/callbacks.py",                    # ProgBarLogger console UI
    "hapi/dynamic_flops.py",                # flops(print_detail=) contract
    "hapi/model_summary.py",                # summary() prints per reference
    "optimizer/lr.py",                      # verbose= knob per reference
    "static/__init__.py",                   # paddle.static.Print op
    "utils/__init__.py",                    # run_check console contract
    "utils/cpp_extension.py",               # verbose build log
}


def _files_with_print():
    out = set()
    for path in sorted(PKG.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                out.add(str(path.relative_to(PKG)))
                break
    return out


def test_no_print_outside_allowlist():
    printing = _files_with_print()
    new = printing - PRINT_ALLOWLIST
    assert not new, (
        f"print() in library code: {sorted(new)} — route through logging "
        f"(see paddle_tpu/profiler.py stop_profiler for the pattern) or, "
        f"for a genuine CLI/console contract, extend PRINT_ALLOWLIST with "
        f"a justification comment")


def test_allowlist_is_pruned():
    printing = _files_with_print()
    stale = PRINT_ALLOWLIST - printing
    assert not stale, (
        f"allowlist entries with no print() left: {sorted(stale)} — "
        f"remove them so the list stays a real inventory")


def test_profiler_routes_through_logging():
    """The satellite fix this lint exists to protect: stop_profiler's
    summary goes to the module logger / on_summary, never stdout."""
    assert "profiler.py" not in _files_with_print()
