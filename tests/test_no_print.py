"""Library hygiene lint: no ``print()`` in paddle_tpu/ library code.

Since the tpulint PR this is a THIN WRAPPER over the ``no-print`` rule in
``paddle_tpu/analysis`` — the frozen allowlist and the detection logic
live there (single source of truth), so the print policy enforced here and
the one enforced by ``tools/tpulint.py`` / tools/collect_smoke.sh cannot
drift apart.  The policy itself is unchanged: library output must flow
through ``logging`` (or an explicit callback/registry) so serving hosts
can route, rate-limit, and silence it; the allowlist covers modules whose
printing IS their contract, and entries with no print() left are
themselves violations (keeps the list honest in both directions)."""

import functools
import pathlib

from paddle_tpu.analysis import PRINT_ALLOWLIST, RULES, lint_paths

ROOT = pathlib.Path(__file__).parent.parent
PKG = ROOT / "paddle_tpu"


@functools.lru_cache(maxsize=1)
def _no_print_findings():
    findings = lint_paths([PKG], root=ROOT, rules=[RULES["no-print"]])
    # rule-filtered (the engine can emit bad-pragma/syntax-error findings
    # regardless of rule selection — those belong to the tpulint gate, not
    # the print policy); fixtures are the rule's own frozen test corpus,
    # baselined in tools/tpulint_baseline.json, not library violations
    return tuple(f for f in findings if f.rule == "no-print"
                 and not f.path.startswith("paddle_tpu/analysis/fixtures/"))


def test_no_print_outside_allowlist():
    new = sorted({f.path for f in _no_print_findings()
                  if "stale" not in f.message})
    assert not new, (
        f"print() in library code: {new} — route through logging "
        f"(see paddle_tpu/profiler.py stop_profiler for the pattern) or, "
        f"for a genuine CLI/console contract, extend PRINT_ALLOWLIST in "
        f"paddle_tpu/analysis/rules.py with a justification comment")


def test_allowlist_is_pruned():
    stale = sorted({f.path for f in _no_print_findings()
                    if "stale" in f.message})
    assert not stale, (
        f"allowlist entries with no print() left: {stale} — remove them "
        f"from PRINT_ALLOWLIST so the list stays a real inventory")
    missing = sorted(rel for rel in PRINT_ALLOWLIST
                     if not (PKG / rel).is_file())
    assert not missing, (
        f"allowlist entries pointing at deleted files: {missing}")


def test_profiler_routes_through_logging():
    """The satellite fix this lint exists to protect: stop_profiler's
    summary goes to the module logger / on_summary, never stdout."""
    assert "profiler.py" not in PRINT_ALLOWLIST
    assert not lint_paths([PKG / "profiler.py"], root=ROOT,
                          rules=[RULES["no-print"]])
