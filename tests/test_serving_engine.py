"""Continuous-batching engine (paddle_tpu/serving.py): requests join and
leave a running decode batch without perturbing each other, and every
request's output matches what model.generate produces for it solo.

No reference counterpart (the reference's generation_utils admits/retires
whole batches); the oracle here is the framework's own single-request
generation path, which is itself oracle-tested in test_generate.py against
the no-cache forward."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTConfig, GPTModel
from paddle_tpu.serving import ContinuousBatchingEngine


@pytest.fixture(scope="module")
def model_and_params():
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=96,
                    compute_dtype="float32")
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    return model, params


def _solo_greedy(model, params, prompt, n):
    out = model.generate(params, jnp.asarray([prompt], jnp.int32), n,
                         greedy=True)
    return [int(t) for t in np.asarray(out)[0]]


PROMPTS = [[5, 17, 3], [40, 2], [9, 9, 9, 9, 9, 1], [61], [8, 30, 12, 4],
           [77, 13, 2, 5, 6, 7, 8]]


class TestContinuousBatching:
    @pytest.mark.parametrize("k", [1, 4])
    def test_interleaved_matches_solo_generate(self, model_and_params, k):
        """Six requests with different prompt lengths and budgets, admitted
        into 3 slots (so retirement/re-admission happens mid-run): every
        request's tokens equal its solo model.generate output — for both
        per-token sync (k=1) and chunked decode (k=4, where budgets that
        are not chunk multiples force mid-chunk retirement + discard)."""
        model, params = model_and_params
        budgets = [10, 4, 7, 12, 3, 8]
        eng = ContinuousBatchingEngine(model, params, max_slots=3,
                                       max_len=32, prompt_buckets=[8, 16],
                                       ticks_per_sync=k)
        rids = [eng.add_request(p, n) for p, n in zip(PROMPTS, budgets)]
        got = eng.run_to_completion(max_ticks=200)
        assert sorted(got) == sorted(rids)
        for rid, p, n in zip(rids, PROMPTS, budgets):
            assert got[rid] == _solo_greedy(model, params, p, n), \
                f"request {rid} diverged from solo generation (k={k})"

    def test_late_admission_does_not_perturb_running_request(
            self, model_and_params):
        """A request admitted mid-decode must not change the tokens of one
        already running (slot isolation), and vice versa."""
        model, params = model_and_params
        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=32, prompt_buckets=[8])
        r0 = eng.add_request(PROMPTS[0], 12)
        for _ in range(5):            # run r0 alone for 5 ticks
            eng.step()
        r1 = eng.add_request(PROMPTS[1], 6)   # joins while r0 is mid-flight
        got = eng.run_to_completion(max_ticks=100)
        assert got[r0] == _solo_greedy(model, params, PROMPTS[0], 12)
        assert got[r1] == _solo_greedy(model, params, PROMPTS[1], 6)

    @pytest.mark.parametrize("k", [1, 3])
    def test_slot_reuse_after_retirement(self, model_and_params, k):
        """A slot freed by a finished request is reused by a later one and
        the stale cache contents (including a chunked run's discarded-tail
        writes) do not leak into its output."""
        model, params = model_and_params
        eng = ContinuousBatchingEngine(model, params, max_slots=1,
                                       max_len=32, prompt_buckets=[8],
                                       ticks_per_sync=k)
        r0 = eng.add_request(PROMPTS[2], 4)
        r1 = eng.add_request(PROMPTS[3], 9)   # waits for the only slot
        got = eng.run_to_completion(max_ticks=100)
        assert got[r0] == _solo_greedy(model, params, PROMPTS[2], 4)
        assert got[r1] == _solo_greedy(model, params, PROMPTS[3], 9)

    def test_eos_retires_early_and_frees_slot(self, model_and_params):
        """eos_token_id: a request stops at its first EOS emission; the
        freed slot admits the queue's next request."""
        model, params = model_and_params
        probe = ContinuousBatchingEngine(model, params, max_slots=1,
                                         max_len=32, prompt_buckets=[8])
        pr = probe.add_request(PROMPTS[0], 10)
        full = probe.run_to_completion(max_ticks=100)[pr]
        eos = full[3]                  # pretend this token id is EOS; the
        cut = full.index(eos) + 1      # engine stops at its FIRST emission
        eng = ContinuousBatchingEngine(model, params, max_slots=1,
                                       max_len=32, prompt_buckets=[8],
                                       eos_token_id=eos)
        r0 = eng.add_request(PROMPTS[0], 10)
        r1 = eng.add_request(PROMPTS[4], 3)
        got = eng.run_to_completion(max_ticks=100)
        assert got[r0] == full[:cut]   # truncated at first EOS (inclusive)
        assert got[r0][-1] == eos and eos not in got[r0][:-1]
        assert got[r1] == _solo_greedy(model, params, PROMPTS[4], 3)

    def test_compiled_program_count_is_bounded(self, model_and_params):
        """One decode program + one prefill program per bucket, cached on
        the MODEL keyed by engine signature — admission order, request
        count, and even fresh engine instances never add programs."""
        model, params = model_and_params
        model.__dict__.pop("_serving_programs", None)

        def make():
            return ContinuousBatchingEngine(model, params, max_slots=2,
                                            max_len=32, prompt_buckets=[4, 8])

        eng = make()
        for p, n in zip(PROMPTS, [3] * len(PROMPTS)):
            eng.add_request(p, n)
        eng.run_to_completion(max_ticks=200)
        progs = model._serving_programs
        before = set(progs)
        assert {kind for kind, *_ in before} == {"prefill", "decode"}
        assert len(before) <= 3                  # <= len(buckets) + 1

        eng2 = make()                            # same signature: no growth
        eng2.add_request(PROMPTS[0], 3)
        eng2.run_to_completion(max_ticks=50)
        assert set(model._serving_programs) == before

    def test_budget_validation(self, model_and_params):
        model, params = model_and_params
        eng = ContinuousBatchingEngine(model, params, max_slots=1,
                                       max_len=16, prompt_buckets=[8])
        with pytest.raises(ValueError, match="bucketed prompt"):
            eng.add_request([1, 2, 3], 12)   # bucket 8 + 12 > 16
        with pytest.raises(ValueError, match="empty"):
            eng.add_request([], 4)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.add_request([1, 2], 0)   # generate() returns empty; the
            # engine would over-generate the prefill token — must refuse
        with pytest.raises(ValueError, match="exceeds the largest bucket"):
            eng.add_request(list(range(12)), 2)

    @pytest.mark.skipif(
        len(jax.devices()) < 4, reason="needs 4 devices")
    def test_tensor_parallel_mesh_matches_single_device(self,
                                                        model_and_params):
        """mp=4 serving: params placed by their _dims_mapping (the training
        path's metadata), cache sharded over heads — every request's tokens
        must equal the single-device engine's."""
        from jax.sharding import Mesh
        model, params = model_and_params
        mesh = Mesh(np.array(jax.devices()[:4]), ("model",))
        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=32, prompt_buckets=[8],
                                       ticks_per_sync=2, mesh=mesh)
        rids = [eng.add_request(p, n)
                for p, n in zip(PROMPTS[:4], [10, 4, 7, 5])]
        got = eng.run_to_completion(max_ticks=200)
        for rid, p, n in zip(rids, PROMPTS[:4], [10, 4, 7, 5]):
            assert got[rid] == _solo_greedy(model, params, p, n), \
                f"TP request {rid} diverged"

    @pytest.mark.parametrize("k", [1, 3])
    def test_repetition_penalty_matches_solo_generate(self, model_and_params,
                                                      k):
        """Engine-wide repetition penalty: the per-slot presence plane must
        reproduce generate()'s processor exactly, across slot reuse (the
        plane row is reset by admission prefill) and chunked decode."""
        model, params = model_and_params
        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=32, prompt_buckets=[8],
                                       ticks_per_sync=k,
                                       repetition_penalty=5.0)
        budgets = [10, 6, 8]
        rids = [eng.add_request(p, n)
                for p, n in zip(PROMPTS[:3], budgets)]
        got = eng.run_to_completion(max_ticks=200)
        for rid, p, n in zip(rids, PROMPTS[:3], budgets):
            solo = model.generate(params, jnp.asarray([p], jnp.int32), n,
                                  greedy=True, repetition_penalty=5.0)
            assert got[rid] == [int(t) for t in np.asarray(solo)[0]], \
                f"request {rid} (k={k})"

    def test_min_new_tokens_per_row_windows(self, model_and_params):
        """Each request's EOS window counts ITS OWN emissions: a request
        admitted mid-run must not inherit the older request's lapsed
        window."""
        model, params = model_and_params
        probe = ContinuousBatchingEngine(model, params, max_slots=1,
                                         max_len=32, prompt_buckets=[8])
        pid = probe.add_request(PROMPTS[0], 8)
        eos = probe.run_to_completion(max_ticks=100)[pid][0]  # emitted 1st
        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=32, prompt_buckets=[8],
                                       ticks_per_sync=2, eos_token_id=eos,
                                       min_new_tokens=4)
        r0 = eng.add_request(PROMPTS[0], 10)
        for _ in range(3):              # r0 is past its window when r1 joins
            eng.step()
        r1 = eng.add_request(PROMPTS[0], 10)  # same prompt: same dynamics
        got = eng.run_to_completion(max_ticks=200)
        for rid in (r0, r1):
            toks = got[rid]
            assert eos not in toks[:4], (rid, toks)
            solo = model.generate(params, jnp.asarray([PROMPTS[0]],
                                                      jnp.int32), 10,
                                  greedy=True, min_new_tokens=4,
                                  eos_token_id=int(eos))
            solo_l = [int(t) for t in np.asarray(solo)[0]]
            if eos in solo_l:
                solo_l = solo_l[:solo_l.index(eos) + 1]
            assert toks == solo_l, (rid, toks, solo_l)

    @pytest.mark.parametrize("penalty", [1.0, 5.0])
    def test_chunked_prefill_matches_whole_prefill(self, model_and_params,
                                                   penalty):
        """prefill_chunk=4 over a 16-bucket: segment-by-segment admission
        (the chunk decode path) must produce exactly the tokens of
        whole-bucket prefill, for ragged (left-padded) prompts, with and
        without the presence-tracking processor."""
        model, params = model_and_params
        prompts = [list(range(3, 17)), [7, 8, 9], list(range(40, 50))]

        def run(chunk):
            eng = ContinuousBatchingEngine(
                model, params, max_slots=2, max_len=32, prompt_buckets=[16],
                ticks_per_sync=2, prefill_chunk=chunk,
                repetition_penalty=penalty)
            rids = [eng.add_request(p, 8) for p in prompts]
            got = eng.run_to_completion(max_ticks=300)
            return [got[r] for r in rids]

        assert run(4) == run(None)

    def test_chunked_admission_into_used_slot_under_decode(
            self, model_and_params):
        """ADVICE r4 (high): the batched-decode presence scatter ran
        unguarded for INACTIVE rows, so while a slot chunk-filled (its
        presence row already reset by segment 0) every concurrent decode
        tick re-marked the slot's stale ``_tok`` — the previous occupant's
        last token — and the new request wrongly repetition-penalized that
        token forever.  The triggering schedule the original fuzz missed:
        one whole-bucket request decoding THROUGHOUT, a short request that
        uses and frees a slot, then a chunked admission into that used
        slot with repetition_penalty > 1."""
        model, params = model_and_params
        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=64, prompt_buckets=[4, 16],
                                       ticks_per_sync=1, prefill_chunk=4,
                                       repetition_penalty=5.0)
        finished = {}
        r0 = eng.add_request(PROMPTS[1], 30)   # bucket 4: whole prefill;
        r1 = eng.add_request([61], 2)          # decodes the whole test
        while True:                            # r1 occupies then frees slot
            eng.step()
            finished.update(eng.pop_finished())
            if r1 in finished:
                break
        # chunked admission (bucket 16 > chunk 4: fills over 4 rounds with
        # r0 decoding next door) into the slot r1 just vacated
        r2 = eng.add_request(list(range(20, 31)), 20)
        for _ in range(300):
            eng.step()
            finished.update(eng.pop_finished())
            if not eng.pending():
                break
        for rid, p, n in [(r0, PROMPTS[1], 30), (r1, [61], 2),
                          (r2, list(range(20, 31)), 20)]:
            solo = model.generate(params, jnp.asarray([p], jnp.int32), n,
                                  greedy=True, repetition_penalty=5.0)
            assert finished[rid] == [int(t) for t in np.asarray(solo)[0]], \
                f"request {rid} diverged (presence pollution)"

    def test_chunked_prefill_keeps_decode_flowing(self, model_and_params):
        """While a long prompt fills over several rounds, an already-active
        request must emit a token every round — the head-of-line fix this
        feature exists for."""
        model, params = model_and_params
        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=48, prompt_buckets=[16],
                                       prefill_chunk=4)
        r0 = eng.add_request(PROMPTS[0], 20)
        eng.step()                          # r0 active (filled in 4 rounds?)
        while not eng._active.any():
            eng.step()
        base = len(eng._slot_req[int(np.flatnonzero(eng._active)[0])]
                   .generated)
        r1 = eng.add_request(list(range(1, 16)), 4)   # long prompt: 4 segs
        for i in range(3):                  # r1 still filling these rounds
            eng.step()
            assert r1 not in eng.pop_finished()
            slot0 = int(np.flatnonzero(eng._active)[0])
            got = len(eng._slot_req[slot0].generated)
            assert got == base + (i + 1), "decode stalled behind prefill"
        got_all = eng.run_to_completion(max_ticks=200)
        assert sorted(got_all) == sorted([r0, r1])

    def test_chunked_fill_survives_concurrent_decode_stale_writes(
            self, model_and_params):
        """THE corruption scenario: a full-bucket (pad=0) prompt fills
        chunk-by-chunk in a fresh slot while another request decodes.  The
        batched decode program stale-writes EVERY row's cache at its clock
        each tick — without clock PARKING those writes land inside [0, P)
        of the filling slot, clobbering prompt k/v that was just written
        (position 0 is unmasked when pad=0).  Greedy tokens are too robust
        to witness a two-position corruption, so this checks the CACHE
        itself against model.prefill's reference — and proves the check is
        live by re-running with the parking sabotaged."""
        model, params = model_and_params
        long_prompt = list(range(3, 19))              # len 16 == bucket: pad 0

        def fill_next_to_decoder(sabotage):
            eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                           max_len=32, prompt_buckets=[16],
                                           ticks_per_sync=2, prefill_chunk=4)
            r0 = eng.add_request(PROMPTS[0], 14)      # decoding throughout
            for _ in range(5):                        # r0 fills, then decodes
                eng.step()
            assert eng._active.any()
            r1 = eng.add_request(long_prompt, 8)
            eng.step()                                # r1's first segment
            slot = next(iter(eng._filling))
            if sabotage:
                eng._t[slot] = 0                      # un-park the clock
            while slot in eng._filling:
                eng.step()
            return np.asarray(eng.caches[0][:, slot, :16])

        ref = model.prefill(params, jnp.asarray([long_prompt], jnp.int32),
                            16)[1][0]
        ref = np.asarray(ref[:, 0, :16])
        good = fill_next_to_decoder(sabotage=False)
        np.testing.assert_allclose(good, ref, rtol=1e-4, atol=1e-5,
                                   err_msg="stale decode writes corrupted "
                                           "the filling slot's prompt cache")
        bad = fill_next_to_decoder(sabotage=True)
        assert np.abs(bad - ref).max() > 0.1, \
            "negative control failed: sabotaged parking should corrupt"

    def test_prefill_chunk_must_divide_buckets(self, model_and_params):
        model, params = model_and_params
        with pytest.raises(ValueError, match="must divide"):
            ContinuousBatchingEngine(model, params, max_slots=1, max_len=32,
                                     prompt_buckets=[8, 12],
                                     prefill_chunk=8)

    def test_sampling_mode_runs_and_respects_budget(self, model_and_params):
        """Sampling engines produce exactly max_new_tokens valid ids (the
        distributional properties of the shared sampler are oracle-tested in
        test_generate; here we pin the scheduler contract)."""
        model, params = model_and_params
        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=32, prompt_buckets=[8],
                                       greedy=False, temperature=0.9,
                                       top_k=20, key=jax.random.key(3))
        r0 = eng.add_request(PROMPTS[0], 6)
        r1 = eng.add_request(PROMPTS[1], 6)
        got = eng.run_to_completion(max_ticks=100)
        for rid in (r0, r1):
            assert len(got[rid]) == 6
            assert all(0 <= t < model.config.vocab_size for t in got[rid])


class TestEngineMetrics:
    def test_metrics_and_stat_registry(self, model_and_params):
        """metrics() reports finished/tokens/TTFT/latency/throughput and
        the global StatRegistry sees the serving counters."""
        from paddle_tpu.utils.stats import get_stat
        model, params = model_and_params
        before = get_stat("serving_tokens_emitted") or 0
        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=32, prompt_buckets=[8])
        eng.add_request(PROMPTS[0], 6)
        eng.add_request(PROMPTS[1], 4)
        eng.run_to_completion(max_ticks=100)
        m = eng.metrics()
        assert m["requests_finished"] == 2
        assert m["tokens_emitted"] == 10
        assert 0 < m["mean_ttft_s"] <= m["mean_latency_s"]
        assert m["tokens_per_sec"] > 0
        assert (get_stat("serving_tokens_emitted") or 0) == before + 10


class TestSchedulerFuzz:
    @pytest.mark.slow
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_scenarios_match_solo(self, model_and_params, seed):
        """Randomized composition stress: random prompts/budgets/admission
        times under randomly drawn engine configs (ticks_per_sync,
        prefill_chunk, eos, repetition penalty, int8 cache) — every
        request's tokens must equal generate() with the same knobs.  The
        scheduler features compose; pairwise tests can't cover the grid."""
        import paddle_tpu as _paddle
        from paddle_tpu.models.gpt import GPTConfig, GPTModel
        rng = np.random.RandomState(seed)
        kv = "int8" if rng.rand() < 0.5 else None
        _paddle.seed(11)   # same seed as the fixture: identical weights
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=96,
                        compute_dtype="float32", kv_cache_dtype=kv)
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}

        ticks = int(rng.choice([1, 2, 4]))
        chunk = int(rng.choice([0, 4, 8]))        # 0 = whole-bucket
        penalty = float(rng.choice([1.0, 4.0]))
        eos = int(rng.randint(0, 97)) if rng.rand() < 0.5 else None
        eng = ContinuousBatchingEngine(
            model, params, max_slots=int(rng.randint(1, 4)), max_len=48,
            prompt_buckets=[8, 16], ticks_per_sync=ticks,
            prefill_chunk=chunk or None, repetition_penalty=penalty,
            eos_token_id=eos)

        reqs = []
        for _ in range(int(rng.randint(4, 9))):
            p = [int(t) for t in rng.randint(1, 97, rng.randint(1, 15))]
            n = int(rng.randint(1, 12))
            reqs.append((eng.add_request(p, n), p, n))
            for _ in range(int(rng.randint(0, 3))):  # staggered admission
                eng.step()
        got = eng.run_to_completion(max_ticks=500)

        for rid, p, n in reqs:
            solo = model.generate(params, jnp.asarray([p], jnp.int32), n,
                                  greedy=True, repetition_penalty=penalty)
            want = [int(t) for t in np.asarray(solo)[0]]
            if eos is not None and eos in want:
                want = want[:want.index(eos) + 1]
            assert got[rid] == want, (
                f"seed={seed} rid={rid} ticks={ticks} chunk={chunk} "
                f"penalty={penalty} eos={eos} kv={kv}")


class TestCrossFamily:
    def test_engine_serves_ernie_moe(self):
        """The engine is model-agnostic over the CausalDecoderMixin
        contract: ERNIE-MoE (gather-dispatch MoE blocks, its own
        decode_step) serves with solo-generate parity, including chunked
        sync and mid-flight admission."""
        from paddle_tpu.models.ernie_moe import ErnieMoeConfig, ErnieMoeModel
        paddle.seed(13)
        cfg = ErnieMoeConfig(vocab_size=89, hidden_size=32, num_layers=2,
                             num_attention_heads=4, num_experts=4, top_k=2,
                             max_position_embeddings=48,
                             compute_dtype="float32")
        model = ErnieMoeModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=32, prompt_buckets=[8],
                                       ticks_per_sync=2)
        prompts = [[5, 17, 3], [40, 2], [9, 8, 7, 1]]
        r = [eng.add_request(p, 6) for p in prompts[:2]]
        eng.step()
        r.append(eng.add_request(prompts[2], 6))   # joins mid-decode
        got = eng.run_to_completion(max_ticks=100)
        for rid, p in zip(r, prompts):
            solo = model.generate(params, jnp.asarray([p], jnp.int32), 6,
                                  greedy=True)
            assert got[rid] == [int(t) for t in np.asarray(solo)[0]], \
                f"ERNIE-MoE request {rid} diverged"


class TestStreaming:
    def test_on_token_streams_in_order(self, model_and_params):
        """Streaming callback: every accepted token arrives exactly once, in
        order, with done on the last — matching the final result, across
        chunked sync (bursts per sync) and EOS retirement."""
        model, params = model_and_params
        seen = {}

        def cb(rid, tok, done):
            seen.setdefault(rid, []).append((tok, done))

        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=32, prompt_buckets=[8],
                                       ticks_per_sync=3)
        r0 = eng.add_request(PROMPTS[0], 7, on_token=cb)
        r1 = eng.add_request(PROMPTS[1], 4, on_token=cb)
        got = eng.run_to_completion(max_ticks=100)
        for rid in (r0, r1):
            toks = [t for t, _ in seen[rid]]
            dones = [d for _, d in seen[rid]]
            assert toks == got[rid]
            assert dones == [False] * (len(toks) - 1) + [True]

    def test_raising_callback_does_not_desync_scheduler(self,
                                                        model_and_params):
        """ADVICE r4 (low): a user callback that raises must not escape
        mid-sync-block — host state (_t/_tok, swapped caches) would desync
        from the unprocessed tail of the token block.  The engine logs and
        drops; outputs stay oracle-exact for every request."""
        model, params = model_and_params
        calls = []

        def bad_cb(rid, tok, done):
            calls.append(tok)
            raise RuntimeError("user callback exploded")

        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=32, prompt_buckets=[8],
                                       ticks_per_sync=3)
        r0 = eng.add_request(PROMPTS[0], 7, on_token=bad_cb)
        r1 = eng.add_request(PROMPTS[1], 4)
        got = eng.run_to_completion(max_ticks=100)
        assert got[r0] == _solo_greedy(model, params, PROMPTS[0], 7)
        assert got[r1] == _solo_greedy(model, params, PROMPTS[1], 4)
        assert calls == got[r0]          # invoked once per token, in order


class TestCancel:
    """Engine.cancel(rid) on the CONTIGUOUS engine (ISSUE 9): slot release
    at every lifecycle stage, the terminal ``(None, True)`` stream signal,
    and undisturbed neighbours."""

    def test_cancel_active_and_queued(self, model_and_params):
        model, params = model_and_params
        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=32, prompt_buckets=[8])
        sig = []
        r0 = eng.add_request(PROMPTS[0], 20,
                             on_token=lambda r, t, d: sig.append((r, t, d)))
        r1 = eng.add_request(PROMPTS[1], 6)
        r2 = eng.add_request(PROMPTS[3], 4)          # queued behind 2 slots
        for _ in range(3):
            eng.step()
        assert eng.cancel(r0)                        # active mid-decode
        assert sig[-1] == (r0, None, True)
        assert eng.cancel(r2)                        # still queued
        assert not eng.cancel(999)
        got = eng.run_to_completion(max_ticks=100)
        assert sorted(got) == [r1]
        assert got[r1] == _solo_greedy(model, params, PROMPTS[1], 6)
        assert not eng.cancel(r1)                    # already finished
        assert eng.metrics()["requests_cancelled"] == 2
        # the freed slots admit fresh work, oracle-exact
        r3 = eng.add_request(PROMPTS[4], 5)
        got = eng.run_to_completion(max_ticks=100)
        assert got[r3] == _solo_greedy(model, params, PROMPTS[4], 5)

    def test_cancel_per_request_planes_reset(self, model_and_params):
        """Cancelling a request with per-request sampling overrides must
        reset the slot's plane rows to the engine defaults — the next
        occupant decodes with ITS config, not the cancelled one's."""
        model, params = model_and_params
        eng = ContinuousBatchingEngine(model, params, max_slots=1,
                                       max_len=32, prompt_buckets=[8],
                                       per_request_sampling=True)
        rid = eng.add_request(PROMPTS[0], 20, repetition_penalty=5.0)
        eng.step()
        slot = next(s for s, r in enumerate(eng._slot_req)
                    if r is not None and r.id == rid)
        assert eng._r_rp[slot] == 5.0
        assert eng.cancel(rid)
        assert eng._r_rp[slot] == eng._plane_defaults[4]   # default rp
        r2 = eng.add_request(PROMPTS[1], 5)                # no overrides
        got = eng.run_to_completion(max_ticks=100)
        assert got[r2] == _solo_greedy(model, params, PROMPTS[1], 5)
