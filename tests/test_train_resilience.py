"""Training resilience (ISSUE 20): the two-phase checkpoint commit
protocol, torn/corrupt-step resolution, crash-mid-save fuzz, preemption
discipline, and the supervisor's chaos pin — under a seeded fault plan the
resumed loss trajectory must equal the uninterrupted oracle bit-exactly,
and corrupt state must NEVER be loaded (skipped and counted, not raised).
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import train_resilience as tr
from paddle_tpu.distributed.checkpoint import CorruptCheckpoint
from paddle_tpu.faults import (Fault, FaultPlan, corrupt_file, torn_write)
from paddle_tpu.jit.functional import fold_in_step_key, make_train_step
from paddle_tpu.optimizer import Momentum
from paddle_tpu.telemetry import Tracer
from paddle_tpu.train_resilience import (CheckpointManager, PreemptionGuard,
                                         RestartBudgetExhausted,
                                         ResumableIterator, TrainSupervisor,
                                         pack_train_state, unpack_train_state)

needs8 = pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 devices")


def _bundle_of(step, dtype=jnp.float32):
    """Deterministic per-step bundle so bit-exactness is checkable from
    the step number alone (the fuzz children regenerate these)."""
    base = jnp.arange(64, dtype=jnp.float32) * (step + 1)
    return {"w": base.astype(dtype), "b": jnp.float32(step * 0.5),
            "step": step}


def _assert_bundle(bundle, step, dtype=jnp.float32):
    want = _bundle_of(step, dtype)
    for k in ("w", "b"):
        a, b = np.asarray(bundle[k]), np.asarray(want[k])
        assert a.dtype == b.dtype, k
        assert a.tobytes() == b.tobytes(), k  # bit-exact, any dtype
    assert int(bundle["step"]) == step


# --------------------------------------------------------------------------
# commit protocol
# --------------------------------------------------------------------------
class TestCommitProtocol:
    def test_two_phase_layout_and_manifest(self, tmp_path):
        m = CheckpointManager(str(tmp_path))
        h = m.save(_bundle_of(3), 3)
        assert h.wait() and h.committed
        d = m.step_path(3)
        names = set(os.listdir(d))
        assert "COMMIT" in names and "ckpt.manifest.json" in names
        with open(os.path.join(d, "ckpt.manifest.json")) as f:
            manifest = json.load(f)
        assert manifest["step"] == 3
        from paddle_tpu.distributed.sharding_rules import \
            sharding_rules_digest
        assert manifest["sharding_rules_digest"] == sharding_rules_digest()
        # every payload file is digested with its byte size
        payload = [n for n in names
                   if n not in ("COMMIT", "ckpt.manifest.json")]
        assert set(manifest["files"]) == set(payload)
        for fname, rec in manifest["files"].items():
            assert rec["bytes"] == os.path.getsize(os.path.join(d, fname))
            assert len(rec["blake2b"]) == 32  # blake2b-16 hex
        # COMMIT seals the manifest, so a swapped manifest is detectable
        with open(os.path.join(d, "COMMIT")) as f:
            marker = json.load(f)
        assert marker["step"] == 3 and marker["manifest_blake2b"]
        assert m.verify(3) == (True, None)

    def test_latest_skips_uncommitted_step(self, tmp_path):
        m = CheckpointManager(str(tmp_path))
        m.save(_bundle_of(1), 1).wait()
        m.save(_bundle_of(2), 2).wait()
        os.remove(os.path.join(m.step_path(2), "COMMIT"))
        assert m.latest() == 1
        assert m.skips == {"uncommitted": 1}
        # counted once per (step, reason), not once per latest() call
        assert m.latest() == 1
        assert m.skips == {"uncommitted": 1}

    @pytest.mark.parametrize("damage,reason", [
        ("truncate", "size_mismatch"),
        ("flip", "digest_mismatch"),
        ("delete", "missing_file"),
        ("manifest", "bad_manifest"),
    ])
    def test_latest_skips_damaged_newest(self, tmp_path, damage, reason):
        m = CheckpointManager(str(tmp_path), tracer=Tracer())
        m.save(_bundle_of(1), 1).wait()
        m.save(_bundle_of(2), 2).wait()
        d = m.step_path(2)
        victim = sorted(f for f in os.listdir(d) if f.endswith(".npy"))[0]
        rng = __import__("random").Random(0)
        if damage == "truncate":
            torn_write(os.path.join(d, victim), rng)
        elif damage == "flip":
            corrupt_file(os.path.join(d, victim), rng)
        elif damage == "delete":
            os.remove(os.path.join(d, victim))
        else:
            with open(os.path.join(d, "ckpt.manifest.json"), "w") as f:
                f.write("{not json")
        assert m.verify(2) == (False, reason)
        assert m.latest() == 1
        assert m.skips == {reason: 1}
        ev = m.tracer.events("train_resilience")
        assert [e for e in ev if e["what"] == "corrupt_skip"
                and e["step"] == 2 and e["reason"] == reason]
        # the skipped step is NEVER loaded; the prior one restores whole
        step, bundle = m.restore(_bundle_of(0))
        assert step == 1
        _assert_bundle(bundle, 1)

    def test_restore_explicit_bad_step_raises_structured(self, tmp_path):
        m = CheckpointManager(str(tmp_path))
        m.save(_bundle_of(1), 1).wait()
        os.remove(os.path.join(m.step_path(1), "COMMIT"))
        with pytest.raises(CorruptCheckpoint, match="uncommitted"):
            m.restore(_bundle_of(0), step=1)
        with pytest.raises(CorruptCheckpoint, match="no committed"):
            m.restore(_bundle_of(0))

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_roundtrip_bit_exact(self, tmp_path, dtype):
        m = CheckpointManager(str(tmp_path))
        m.save(_bundle_of(5, dtype), 5).wait()
        step, bundle = m.restore(_bundle_of(0, dtype))
        assert step == 5
        _assert_bundle(bundle, 5, dtype)

    def test_deadline_miss_abandons_and_prior_stays_valid(self, tmp_path):
        ticks = iter(range(0, 10_000, 100))  # each clock() read jumps 100s
        m = CheckpointManager(str(tmp_path), tracer=Tracer(),
                              clock=lambda: float(next(ticks)))
        m.save(_bundle_of(1), 1).wait()
        h = m.save(_bundle_of(2), 2, deadline_s=1.0)
        assert h.wait() is False and not h.committed
        assert not os.path.exists(os.path.join(m.step_path(2), "COMMIT"))
        assert m.latest() == 1            # prior step still the resume point
        assert m.registry.value("saves_abandoned") == 1
        ab = [e for e in m.tracer.events("train_resilience")
              if e["what"] == "save_abandon"]
        assert ab and ab[0]["reason"] == "deadline"

    def test_gc_retention_and_keep_every_pinning(self, tmp_path):
        m = CheckpointManager(str(tmp_path), retain=2, keep_every=4)
        for s in range(1, 11):
            m.save(_bundle_of(s), s).wait()
        removed = m.gc()
        kept = m.steps()
        assert kept == [4, 8, 9, 10]      # 2 newest + keep_every pins
        assert removed == [1, 2, 3, 5, 6, 7]
        # uncommitted junk older than newest committed is swept too
        os.makedirs(m.step_path(6))
        m.gc()
        assert 6 not in m.steps()

    def test_async_save_commit_chain(self, tmp_path):
        m = CheckpointManager(str(tmp_path))
        h = m.save(_bundle_of(7), 7, async_save=True)
        assert h.wait() is True and h.done() and h.committed
        assert m.latest() == 7
        step, bundle = m.restore(_bundle_of(0))
        _assert_bundle(bundle, 7)

    def test_resave_supersedes_torn_dir(self, tmp_path):
        m = CheckpointManager(str(tmp_path))
        m.save(_bundle_of(2), 2).wait()
        os.remove(os.path.join(m.step_path(2), "COMMIT"))
        m.save(_bundle_of(2), 2).wait()   # restart replays the same step
        assert m.verify(2) == (True, None)
        assert m.latest() == 2

    def test_rules_digest_mismatch_is_nonfatal(self, tmp_path):
        import hashlib
        m = CheckpointManager(str(tmp_path), tracer=Tracer())
        m.save(_bundle_of(1), 1).wait()
        d = m.step_path(1)
        mpath = os.path.join(d, "ckpt.manifest.json")
        with open(mpath) as f:
            manifest = json.load(f)
        manifest["sharding_rules_digest"] = "stale-rules"
        raw = json.dumps(manifest)
        with open(mpath, "w") as f:
            f.write(raw)
        # re-seal so ONLY the rules digest disagrees (a legit rule edit)
        h = hashlib.blake2b(digest_size=16)
        h.update(raw.encode())
        with open(os.path.join(d, "COMMIT"), "w") as f:
            json.dump({"step": 1, "manifest_blake2b": h.hexdigest()}, f)
        assert m.verify(1) == (True, None)       # warns, does not fail
        assert m.rules_mismatch_steps == [1]
        assert [e for e in m.tracer.events("train_resilience")
                if e["what"] == "rules_mismatch"]


# --------------------------------------------------------------------------
# fault primitives (satellite: faults.py torn_write / corrupt_file)
# --------------------------------------------------------------------------
class TestFaultPrimitives:
    def test_torn_write_truncates_seeded(self, tmp_path):
        import random
        p = str(tmp_path / "f.bin")
        with open(p, "wb") as f:
            f.write(bytes(range(256)) * 4)
        kept = torn_write(p, random.Random(3))
        assert 0 < kept < 1024 and os.path.getsize(p) == kept
        # same seed, same tear point
        with open(p, "wb") as f:
            f.write(bytes(range(256)) * 4)
        assert torn_write(p, random.Random(3)) == kept

    def test_corrupt_file_flips_in_place(self, tmp_path):
        import random
        p = str(tmp_path / "f.bin")
        payload = bytes(range(256)) * 4
        with open(p, "wb") as f:
            f.write(payload)
        flipped = corrupt_file(p, random.Random(5), n_bytes=4)
        assert flipped == 4
        with open(p, "rb") as f:
            after = f.read()
        assert len(after) == len(payload) and after != payload

    def test_manager_consumes_fs_faults_on_save_ordinal_clock(self, tmp_path):
        plan = FaultPlan([Fault("torn_write", at_s=1, count=1),
                          Fault("corrupt_file", at_s=2, count=1)], seed=11)
        m = CheckpointManager(str(tmp_path), fault_plan=plan, tracer=Tracer())
        assert m.save(_bundle_of(0), 0).wait() is True    # ordinal 0: clean
        assert m.save(_bundle_of(1), 1).wait() is False   # ordinal 1: torn
        assert m.save(_bundle_of(2), 2).wait() is True    # ordinal 2: commits
        # ...but the post-commit corruption must be caught by resolution
        assert m.latest() == 0
        assert m.skips.get("uncommitted") == 1            # the torn step
        assert m.skips.get("digest_mismatch") == 1        # the corrupted one
        step, bundle = m.restore(_bundle_of(0))
        _assert_bundle(bundle, 0)


# --------------------------------------------------------------------------
# crash-mid-save fuzz (satellite: subprocess SIGKILL at random points)
# --------------------------------------------------------------------------
_FUZZ_CHILD = r"""
import os, signal, sys, time
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
import jax.numpy as jnp
from paddle_tpu.train_resilience import CheckpointManager

root, delay_us = sys.argv[1], int(sys.argv[2])
m = CheckpointManager(root)
for s in range(3):
    base = jnp.arange(1 << 18, dtype=jnp.float32) * (s + 1)
    assert m.save({{"w": base, "step": s}}, s).wait()
# big payload so the async save is genuinely in flight when the kill lands
s = 3
big = jnp.arange(1 << 18, dtype=jnp.float32) * (s + 1)
m.save({{"w": big, "step": s}}, s, async_save=True)
time.sleep(delay_us / 1e6)
os.kill(os.getpid(), signal.SIGKILL)
"""


@pytest.mark.parametrize("delay_us", [0, 2_000, 15_000, 60_000])
def test_sigkill_mid_async_save_never_loads_torn(tmp_path, delay_us):
    """Property: whatever instant the process dies at, ``latest()`` is a
    COMMITted step whose restore is bit-exact — a torn step-3 dir is
    skipped, a completed one is used."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = str(tmp_path / "ck")
    proc = subprocess.run(
        [sys.executable, "-c", _FUZZ_CHILD.format(repo=repo),
         root, str(delay_us)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == -signal.SIGKILL, proc.stderr
    m = CheckpointManager(root)
    got = m.latest()
    assert got in (2, 3)                  # never None, never a torn step
    assert m.verify(got) == (True, None)
    template = {"w": jnp.zeros(1 << 18, jnp.float32), "step": 0}
    step, bundle = m.restore(template)
    np.testing.assert_array_equal(
        np.asarray(bundle["w"]),
        np.arange(1 << 18, dtype=np.float32) * (step + 1))
    assert int(bundle["step"]) == step
    # the fsck CLI agrees: the root is resumable
    from tools.ckpt_fsck import main as fsck
    assert fsck([root, "verify", "--json"]) == 0


@pytest.mark.parametrize("seed", range(5))
def test_random_damage_fuzz_always_resolves_prior_step(tmp_path, seed):
    """In-process fuzz: random damage to the newest step dir (torn file,
    flipped bytes, deleted payload/COMMIT/manifest, garbage manifest) —
    resolution must always land on the intact prior step, bit-exact."""
    import random
    rng = random.Random(seed)
    m = CheckpointManager(str(tmp_path / f"r{seed}"))
    m.save(_bundle_of(1), 1).wait()
    m.save(_bundle_of(2), 2).wait()
    d = m.step_path(2)
    payload = sorted(f for f in os.listdir(d) if f.endswith(".npy"))
    mode = rng.choice(["torn", "flip", "del_payload", "del_commit",
                       "garbage_manifest"])
    if mode == "torn":
        torn_write(os.path.join(d, rng.choice(payload)), rng)
    elif mode == "flip":
        corrupt_file(os.path.join(d, rng.choice(payload)), rng)
    elif mode == "del_payload":
        os.remove(os.path.join(d, rng.choice(payload)))
    elif mode == "del_commit":
        os.remove(os.path.join(d, "COMMIT"))
    else:
        with open(os.path.join(d, "ckpt.manifest.json"), "w") as f:
            f.write("\x00garbage")
    assert m.latest() == 1
    step, bundle = m.restore(_bundle_of(0))
    assert step == 1
    _assert_bundle(bundle, 1)
    assert sum(m.skips.values()) == 1


# --------------------------------------------------------------------------
# full-state capture: typed RNG keys, comm_e residual, update-sharded R=2
# --------------------------------------------------------------------------
class TestStateCapture:
    def test_pack_unpack_typed_key_roundtrip(self):
        key = jax.random.key(7)
        b = pack_train_state({"p": jnp.ones(3)}, step=4, base_key=key,
                             data_state={"epoch": 1, "offset": 9})
        state, step, key2, data = unpack_train_state(b)
        assert step == 4 and data == {"epoch": 1, "offset": 9}
        np.testing.assert_array_equal(jax.random.key_data(key),
                                      jax.random.key_data(key2))
        # the restored key derives identical per-step keys
        np.testing.assert_array_equal(
            jax.random.key_data(fold_in_step_key(key, 11)),
            jax.random.key_data(fold_in_step_key(key2, 11)))

    def test_pack_unpack_legacy_uint32_key(self):
        key = jax.random.PRNGKey(3)
        b = pack_train_state({}, step=0, base_key=key)
        _, _, key2, _ = unpack_train_state(b)
        np.testing.assert_array_equal(np.asarray(key), np.asarray(key2))

    def test_int8_ef_comm_residual_roundtrips(self, tmp_path):
        layer = nn.Linear(8, 4)
        step_fn, state = make_train_step(
            layer, nn.MSELoss(), Momentum(learning_rate=0.1, momentum=0.9),
            grad_comm="int8_ef")
        assert "comm_e" in state
        key = jax.random.PRNGKey(0)
        x = jnp.asarray(np.random.RandomState(1).randn(4, 8), jnp.float32)
        y = jnp.asarray(np.random.RandomState(2).randn(4, 4), jnp.float32)
        state, _ = step_fn(state, key, np.float32(0.1), [x], [y])
        m = CheckpointManager(str(tmp_path))
        m.save(pack_train_state(state, step=1), 1).wait()
        _, bundle = m.restore(pack_train_state(state, step=1))
        restored, *_ = unpack_train_state(bundle)
        flat_a = jax.tree_util.tree_leaves(state)
        flat_b = jax.tree_util.tree_leaves(restored)
        for a, b in zip(flat_a, flat_b):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @needs8
    def test_update_sharded_r2_capture_restore_bit_exact(self, tmp_path):
        """The 1/R flat slot shard + per-replica comm_e round-trip through
        the manager and the resumed trajectory continues exactly."""
        from jax.sharding import Mesh
        from paddle_tpu.distributed import make_dp_update_sharded_train_step
        from paddle_tpu.optimizer import SGD

        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        rng = np.random.default_rng(0)
        params = {"w": jnp.asarray(rng.normal(size=(8, 4)) * 0.1,
                                   jnp.float32),
                  "b": jnp.zeros((4,), jnp.float32)}

        def loss_of(p, x, y):
            return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

        def batch(seed):
            r = np.random.default_rng(seed)
            return (jnp.asarray(r.normal(size=(4, 8)), jnp.float32),
                    jnp.asarray(r.normal(size=(4, 4)), jnp.float32))

        step_fn, state = make_dp_update_sharded_train_step(
            loss_of, params, SGD(0.05), mesh, grad_comm="int8_ef",
            donate=False)
        lr = np.float32(0.05)
        for s in range(3):
            state, _ = step_fn(state, lr, *batch(s))

        m = CheckpointManager(str(tmp_path))
        m.save(pack_train_state(state, step=3), 3).wait()
        shardings = {"train": jax.tree_util.tree_map(
            lambda a: a.sharding if isinstance(a, jax.Array) else None,
            state)}
        _, bundle = m.restore(pack_train_state(state, step=3),
                              shardings=shardings)
        restored, *_ = unpack_train_state(bundle)
        for a, b in zip(jax.tree_util.tree_leaves(state),
                        jax.tree_util.tree_leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # trajectory continuity: original vs restored, two more steps
        sa, sb = state, restored
        for s in range(3, 5):
            sa, la = step_fn(sa, lr, *batch(s))
            sb, lb = step_fn(sb, lr, *batch(s))
            assert float(la) == float(lb)


# --------------------------------------------------------------------------
# supervisor: the chaos pin
# --------------------------------------------------------------------------
def _tiny_trainer(seed=0):
    paddle.seed(seed)
    layer = nn.Linear(8, 4)
    step_fn, state = make_train_step(
        layer, nn.MSELoss(), Momentum(learning_rate=0.1, momentum=0.9))
    r = np.random.RandomState(seed + 1)
    batches = [([jnp.asarray(r.randn(4, 8), jnp.float32)],
                [jnp.asarray(r.randn(4, 4), jnp.float32)]) for _ in range(8)]
    return step_fn, state, ResumableIterator(batches)


def _supervisor(tmp_path, name, fault_plan=None, **kw):
    step_fn, state, data = _tiny_trainer()
    m = CheckpointManager(str(tmp_path / name), tracer=Tracer(),
                          fault_plan=fault_plan)
    kw.setdefault("save_every", 4)
    kw.setdefault("backoff_s", 0.0)
    return TrainSupervisor(step_fn, state, m,
                           base_key=jax.random.PRNGKey(0), lr=0.1,
                           data=data, fault_plan=fault_plan, **kw)


class TestSupervisorChaosPin:
    def test_oracle_equality_under_seeded_fault_plan(self, tmp_path):
        """THE acceptance pin: alloc_fail x2 + torn_write mid-run; the
        supervised trajectory equals the uninterrupted oracle bit-exactly,
        torn state is counted-skipped, never loaded, never raised."""
        oracle = _supervisor(tmp_path, "oracle").run(20)
        assert oracle["completed"] and len(oracle["losses"]) == 20

        plan = FaultPlan([Fault("alloc_fail", at_s=7, count=1),
                          Fault("alloc_fail", at_s=13, count=1),
                          Fault("torn_write", at_s=3, count=1)], seed=7)
        sup = _supervisor(tmp_path, "chaos", fault_plan=plan)
        res = sup.run(20)
        assert res["completed"]
        assert res["restarts"] == 2
        assert res["steps_replayed"] > 0
        assert res["skips"] == {"uncommitted": 1}     # the torn save
        assert res["losses"] == oracle["losses"]      # bit-exact
        ev = sup.tracer.events("train_resilience")
        whats = {e["what"] for e in ev}
        assert {"save_commit", "save_abandon", "restart", "restore",
                "corrupt_skip", "fault_inject"} <= whats
        # tracer summary section materializes
        summ = sup.tracer.summary()["train_resilience"]
        assert summ["events"]["save_commit"] >= 1
        assert summ["last_commit_step"] == 20

    def test_restart_budget_exhausts_structurally(self, tmp_path):
        plan = FaultPlan([Fault("alloc_fail", at_s=0)], seed=0)  # every step
        sup = _supervisor(tmp_path, "budget", fault_plan=plan,
                          restart_budget=2)
        with pytest.raises(RestartBudgetExhausted):
            sup.run(10)
        assert sup.train_snapshot()["restarts"] == 2

    def test_non_finite_loss_escalates_and_recovers(self, tmp_path):
        step_fn, state, data = _tiny_trainer()
        poisoned = {"armed": True}

        def call(fn, st, key, lr, batch):
            st, (loss, _out) = fn(st, key, lr, *batch)
            if poisoned["armed"]:
                poisoned["armed"] = False
                return st, jnp.float32(np.nan)        # transient NaN blip
            return st, loss

        m = CheckpointManager(str(tmp_path / "nan"), tracer=Tracer())
        sup = TrainSupervisor(step_fn, state, m,
                              base_key=jax.random.PRNGKey(0), lr=0.1,
                              data=data, call=call, save_every=4,
                              backoff_s=0.0)
        res = sup.run(8)
        assert res["completed"] and res["restarts"] == 1
        assert all(np.isfinite(res["losses"]))

    def test_preemption_resume_matches_oracle_tail(self, tmp_path):
        oracle = _supervisor(tmp_path, "o2").run(16)

        def boundary(t, sup):
            if t == 9:
                sup.guard.request()

        guard = PreemptionGuard()                      # not installed: no
        sup = _supervisor(tmp_path, "pre", guard=guard,  # signal plumbing
                          on_boundary=boundary)
        res = sup.run(16)
        assert res["preempted"] and res["final_step"] == 9
        assert sup.manager.latest() == 9               # emergency committed
        ev = [e for e in sup.tracer.events("train_resilience")
              if e["what"] == "preempt_save"]
        assert ev and ev[0]["committed"]

        step_fn2, state2, data2 = _tiny_trainer()
        sup2 = TrainSupervisor(step_fn2, state2, sup.manager,
                               base_key=jax.random.PRNGKey(0), lr=0.1,
                               data=data2, save_every=4, backoff_s=0.0)
        res2 = sup2.run(16)
        assert res2["completed"] and res2["first_step"] == 9
        assert res2["losses"] == oracle["losses"][9:]
        assert res2["final_loss"] == oracle["final_loss"]

    def test_elastic_exit_takes_emergency_checkpoint(self, tmp_path):
        codes = []

        class FakeElastic:
            def exit_code(self):
                return 101 if codes == [] and sup._step >= 5 else None

        sup = _supervisor(tmp_path, "el", elastic=FakeElastic(),
                          elastic_exit=codes.append)
        sup.run(12)
        assert codes == [101]
        assert sup.manager.latest() == sup.train_snapshot()["step"]
        assert [e for e in sup.tracer.events("train_resilience")
                if e["what"] == "elastic_exit"]

    def test_async_save_mode_end_to_end(self, tmp_path):
        oracle = _supervisor(tmp_path, "o3").run(12)
        sup = _supervisor(tmp_path, "async", async_save=True)
        res = sup.run(12)
        assert res["completed"]
        assert res["losses"] == oracle["losses"]
        assert sup.manager.latest() == 12

    def test_train_snapshot_and_prometheus(self, tmp_path):
        sup = _supervisor(tmp_path, "snap")
        sup.run(6)
        snap = sup.train_snapshot()
        for k in ("status", "step", "restarts", "restart_budget",
                  "steps_replayed", "recovery_time_s", "preempted",
                  "checkpoint"):
            assert k in snap, k
        assert snap["status"] == "done"
        assert snap["checkpoint"]["saves_committed"] >= 1
        text = sup.prometheus_text()
        assert "paddle_tpu_train_resilience_" in text


# --------------------------------------------------------------------------
# preemption guard signal discipline
# --------------------------------------------------------------------------
class TestPreemptionGuard:
    def test_sigterm_defers_then_chains_on_release(self):
        hits = []
        prev = signal.signal(signal.SIGTERM, lambda *a: hits.append("prev"))
        try:
            g = PreemptionGuard(tracer=Tracer()).install()
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.time() + 5
            while not g.requested and time.time() < deadline:
                time.sleep(0.01)
            assert g.requested
            assert hits == []                  # deferred, not delivered
            assert [e for e in g.tracer.events("train_resilience")
                    if e["what"] == "preempt_request"]
            g.release()                        # now the chain fires
            assert hits == ["prev"]
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_uninstall_restores_previous_handler(self):
        prev = signal.signal(signal.SIGTERM, lambda *a: None)
        try:
            g = PreemptionGuard().install()
            g.uninstall()
            assert signal.getsignal(signal.SIGTERM) is not g._handler
        finally:
            signal.signal(signal.SIGTERM, prev)


# --------------------------------------------------------------------------
# integration seams: iterator, elastic, ops route, hapi callback, fsck
# --------------------------------------------------------------------------
class TestResumableIterator:
    def test_wraps_epochs_and_seeks(self):
        it = ResumableIterator(["a", "b", "c"])
        got = [it.next_batch() for _ in range(4)]
        assert got == ["a", "b", "c", "a"]
        assert it.state() == {"epoch": 1, "offset": 1}
        it2 = ResumableIterator(["a", "b", "c"])
        it2.seek({"epoch": 1, "offset": 1})
        assert it2.next_batch() == "b"


class TestElasticManagedSave:
    def test_run_with_checkpoint_managed_path(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager
        em = ElasticManager(str(tmp_path / "store"), rank=0)
        em.exit_code = lambda: 101            # membership change happened
        m = CheckpointManager(str(tmp_path / "ck"))
        steps = {"n": 0}

        def train_fn():
            steps["n"] += 1
            return True

        with pytest.raises(SystemExit) as ei:
            em.run_with_checkpoint(
                train_fn, check_every=0.0, manager=m,
                state_fn=lambda: _bundle_of(steps["n"]),
                step_fn=lambda: steps["n"])
        assert ei.value.code == 101
        assert m.latest() == steps["n"]       # rescale save committed

    def test_requires_manager_triple_when_no_save_fn(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager
        em = ElasticManager(str(tmp_path / "store"), rank=0)
        with pytest.raises(ValueError, match="managed two-phase"):
            em.run_with_checkpoint(lambda: False)


class TestOpsRoute:
    def test_get_train_serves_supervisor_snapshot(self, tmp_path):
        import urllib.request
        from paddle_tpu.ops_server import OpsServer
        sup = _supervisor(tmp_path, "ops")
        sup.run(6)
        srv = OpsServer()
        srv.attach(sup, name="trainer")
        url = srv.start()
        try:
            snap = json.loads(urllib.request.urlopen(
                url + "/train", timeout=10).read())
            assert snap["status"] == "done"
            assert snap["checkpoint"]["saves_committed"] >= 1
            metrics = urllib.request.urlopen(
                url + "/metrics", timeout=10).read().decode()
            assert "paddle_tpu_train_resilience_" in metrics
        finally:
            srv.stop()

    def test_get_train_404_when_nothing_attached(self):
        import urllib.error
        import urllib.request
        from paddle_tpu.ops_server import OpsServer
        srv = OpsServer()
        url = srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url + "/train", timeout=10)
            assert ei.value.code == 404
        finally:
            srv.stop()


class TestManagedCheckpointCallback:
    def test_fit_saves_and_resumes(self, tmp_path):
        from paddle_tpu.callbacks import ManagedCheckpoint
        from paddle_tpu.hapi import Model
        from paddle_tpu.io import Dataset
        from paddle_tpu.optimizer import SGD

        rng = np.random.RandomState(0)
        xs = rng.randn(32, 8).astype("float32")
        ys = rng.randn(32, 2).astype("float32")

        class DS(Dataset):
            def __getitem__(self, i):
                return xs[i], ys[i]

            def __len__(self):
                return 32

        def fit(cb, epochs):
            paddle.seed(5)
            net = nn.Linear(8, 2)
            model = Model(net)
            model.prepare(SGD(0.1, parameters=net.parameters()),
                          nn.MSELoss())
            model.fit(DS(), batch_size=8, epochs=epochs, verbose=0,
                      callbacks=[cb])
            return model

        m = CheckpointManager(str(tmp_path / "hapi"))
        fit(ManagedCheckpoint(m), epochs=2)
        assert m.latest() == 2
        cb2 = ManagedCheckpoint(m)
        fit(cb2, epochs=3)
        assert cb2.resumed_epoch == 2
        assert m.latest() == 3


class TestFsckCli:
    def test_verify_list_gc_and_exit_codes(self, tmp_path, capsys):
        from tools.ckpt_fsck import main
        root = str(tmp_path / "ck")
        m = CheckpointManager(root)
        for s in (1, 2, 3):
            m.save(_bundle_of(s), s).wait()
        os.remove(os.path.join(m.step_path(3), "COMMIT"))
        assert main([root, "verify"]) == 0       # degraded but resumable
        out = capsys.readouterr().out
        assert "resume at step 2" in out and "uncommitted" in out
        assert main([root, "verify", "--step", "3"]) == 1
        capsys.readouterr()
        assert main([root, "verify", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["resume_step"] == 2 and doc["broken"] == 1
        assert main([root, "gc", "--retain", "1"]) == 0
        assert main([root, "list", "--json"]) == 0
        capsys.readouterr()
        # an all-broken root is NOT resumable: exit 1
        for s in (1, 2):
            os.remove(os.path.join(m.step_path(s), "COMMIT")) \
                if os.path.exists(os.path.join(m.step_path(s), "COMMIT")) \
                else None
        # steps may have been gc'd; damage whatever remains
        for s in m.steps():
            c = os.path.join(m.step_path(s), "COMMIT")
            if os.path.exists(c):
                os.remove(c)
        assert main([root, "verify"]) == 1
        assert main(["/nonexistent/root", "verify"]) == 1
