"""Tensor-op tests against numpy oracles (reference: unittests/op_test.py
check_output pattern)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def t(x):
    return paddle.to_tensor(np.asarray(x))


class TestCreation:
    def test_basics(self):
        assert paddle.zeros([2, 3]).shape == [2, 3]
        assert paddle.ones([4]).numpy().sum() == 4
        np.testing.assert_allclose(paddle.arange(5).numpy(), np.arange(5))
        np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5))
        assert paddle.full([2], 7).numpy().tolist() == [7, 7]
        np.testing.assert_allclose(paddle.eye(3).numpy(), np.eye(3))
        assert paddle.tril(paddle.ones([3, 3])).numpy()[0, 2] == 0
        assert paddle.triu(paddle.ones([3, 3])).numpy()[2, 0] == 0

    def test_to_tensor_dtype(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], dtype="float64"))
        assert x.dtype == paddle.float32  # default dtype conversion
        y = paddle.to_tensor([1, 2, 3])
        assert "int" in str(y.dtype)


class TestMath:
    def test_elementwise(self):
        a = np.random.randn(3, 4).astype("float32")
        b = np.random.randn(3, 4).astype("float32")
        np.testing.assert_allclose(paddle.add(t(a), t(b)).numpy(), a + b, rtol=1e-6)
        np.testing.assert_allclose(paddle.multiply(t(a), t(b)).numpy(), a * b, rtol=1e-6)
        np.testing.assert_allclose(paddle.maximum(t(a), t(b)).numpy(), np.maximum(a, b))
        np.testing.assert_allclose((t(a) / t(b)).numpy(), a / b, rtol=1e-5)
        np.testing.assert_allclose(paddle.exp(t(a)).numpy(), np.exp(a), rtol=1e-5)

    def test_reductions(self):
        a = np.random.randn(3, 4, 5).astype("float32")
        np.testing.assert_allclose(paddle.sum(t(a), axis=1).numpy(), a.sum(1), rtol=1e-5)
        np.testing.assert_allclose(paddle.mean(t(a)).numpy(), a.mean(), rtol=1e-5)
        np.testing.assert_allclose(paddle.max(t(a), axis=[0, 2]).numpy(), a.max((0, 2)))
        np.testing.assert_allclose(paddle.prod(t(a), axis=-1, keepdim=True).numpy(),
                                   a.prod(-1, keepdims=True), rtol=1e-4)
        np.testing.assert_allclose(paddle.logsumexp(t(a), axis=1).numpy(),
                                   np.log(np.exp(a).sum(1)), rtol=1e-4)  # fp32 accumulation-order slack

    def test_cumsum_cummax(self):
        a = np.random.randn(3, 4).astype("float32")
        np.testing.assert_allclose(paddle.cumsum(t(a), axis=1).numpy(), a.cumsum(1), rtol=1e-5)
        vals, idx = paddle.cummax(t(a), axis=1)
        np.testing.assert_allclose(vals.numpy(), np.maximum.accumulate(a, 1))

    def test_clip_scale(self):
        a = np.random.randn(10).astype("float32")
        np.testing.assert_allclose(paddle.clip(t(a), -0.5, 0.5).numpy(),
                                   np.clip(a, -0.5, 0.5))
        np.testing.assert_allclose(paddle.scale(t(a), 2.0, 1.0).numpy(), a * 2 + 1,
                                   rtol=1e-6)


class TestManipulation:
    def test_reshape_transpose(self):
        a = np.arange(24).reshape(2, 3, 4).astype("float32")
        assert paddle.reshape(t(a), [4, 6]).shape == [4, 6]
        np.testing.assert_allclose(paddle.transpose(t(a), [2, 0, 1]).numpy(),
                                   a.transpose(2, 0, 1))
        assert paddle.flatten(t(a), 1).shape == [2, 12]
        assert paddle.unsqueeze(t(a), [0, 2]).shape == [1, 2, 1, 3, 4]
        assert paddle.squeeze(paddle.ones([1, 3, 1])).shape == [3]

    def test_concat_split_stack(self):
        a = np.random.randn(2, 3).astype("float32")
        c = paddle.concat([t(a), t(a)], axis=0)
        assert c.shape == [4, 3]
        s = paddle.split(c, 2, axis=0)
        np.testing.assert_allclose(s[0].numpy(), a)
        parts = paddle.split(t(a), [1, -1], axis=1)
        assert parts[0].shape == [2, 1] and parts[1].shape == [2, 2]
        st = paddle.stack([t(a), t(a)], axis=1)
        assert st.shape == [2, 2, 3]

    def test_gather_scatter(self):
        a = np.arange(12).reshape(4, 3).astype("float32")
        idx = np.array([0, 2])
        np.testing.assert_allclose(paddle.gather(t(a), t(idx)).numpy(), a[idx])
        upd = np.ones((2, 3), "float32") * 9
        out = paddle.scatter(t(a), t(idx), t(upd))
        expect = a.copy()
        expect[idx] = 9
        np.testing.assert_allclose(out.numpy(), expect)

    def test_pad_tile_flip(self):
        a = np.random.randn(1, 2, 3, 3).astype("float32")
        p = paddle.nn.functional.pad(t(a), [1, 1, 2, 2])
        assert p.shape == [1, 2, 7, 5]
        np.testing.assert_allclose(paddle.tile(t(np.ones((2,), "float32")), [3]).numpy(),
                                   np.tile(np.ones(2), 3))
        np.testing.assert_allclose(paddle.flip(t(a), [3]).numpy(), a[..., ::-1])

    def test_masked_where(self):
        a = np.random.randn(3, 4).astype("float32")
        m = a > 0
        np.testing.assert_allclose(paddle.masked_select(t(a), t(m)).numpy(), a[m])
        np.testing.assert_allclose(paddle.where(t(m), t(a), t(-a)).numpy(),
                                   np.where(m, a, -a))


class TestSearchSort:
    def test_argmax_topk_sort(self):
        a = np.random.randn(4, 6).astype("float32")
        np.testing.assert_allclose(paddle.argmax(t(a), axis=1).numpy(), a.argmax(1))
        vals, idx = paddle.topk(t(a), 3, axis=1)
        np.testing.assert_allclose(vals.numpy(), -np.sort(-a, 1)[:, :3], rtol=1e-6)
        np.testing.assert_allclose(paddle.sort(t(a), axis=0).numpy(), np.sort(a, 0))
        np.testing.assert_allclose(paddle.argsort(t(a), axis=1, descending=True).numpy(),
                                   np.argsort(-a, 1, kind="stable"))

    def test_unique_nonzero(self):
        a = np.array([3, 1, 2, 1, 3])
        u = paddle.unique(t(a))
        np.testing.assert_allclose(u.numpy(), [1, 2, 3])
        nz = paddle.nonzero(t(np.array([0, 1, 0, 2])))
        np.testing.assert_allclose(nz.numpy(), [[1], [3]])


class TestLinalg:
    def test_matmul_family(self):
        a = np.random.randn(3, 4).astype("float32")
        b = np.random.randn(4, 5).astype("float32")
        np.testing.assert_allclose(paddle.matmul(t(a), t(b)).numpy(), a @ b, rtol=1e-5,
                                   atol=1e-5)
        np.testing.assert_allclose(paddle.matmul(t(a.T), t(b), transpose_x=True).numpy(),
                                   a @ b, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(paddle.einsum("ij,jk->ik", t(a), t(b)).numpy(), a @ b,
                                   rtol=1e-5, atol=1e-5)

    def test_decompositions(self):
        a = np.random.randn(4, 4).astype("float32")
        spd = a @ a.T + 4 * np.eye(4, dtype="float32")
        L = paddle.cholesky(t(spd))
        np.testing.assert_allclose((L @ L.T).numpy(), spd, rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(paddle.inv(t(spd)).numpy(), np.linalg.inv(spd),
                                   rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(paddle.norm(t(a)).numpy(), np.linalg.norm(a), rtol=1e-5)
        u, s, vh = paddle.svd(t(a))
        np.testing.assert_allclose((u @ paddle.diag(s) @ vh).numpy(), a, rtol=1e-3,
                                   atol=1e-3)


class TestRandom:
    def test_shapes_and_determinism(self):
        paddle.seed(5)
        a = paddle.randn([3, 4])
        paddle.seed(5)
        b = paddle.randn([3, 4])
        np.testing.assert_allclose(a.numpy(), b.numpy())
        u = paddle.uniform([1000], min=0, max=1)
        assert 0.4 < float(u.mean()) < 0.6
        r = paddle.randint(0, 10, [100])
        assert r.numpy().min() >= 0 and r.numpy().max() < 10
        p = paddle.randperm(10)
        assert sorted(p.numpy().tolist()) == list(range(10))


class TestRandomMoments:
    """Moment/support checks for the remaining random ops (the oracle
    harness waives them as statistical; this is their numeric backstop)."""

    def test_empty_contract(self):
        e = paddle.empty([3, 4], dtype="float32")
        assert list(e.shape) == [3, 4] and e.dtype == "float32"
        el = paddle.empty_like(e)
        assert list(el.shape) == [3, 4]

    def test_bernoulli_poisson_binomial(self):
        paddle.seed(3)
        p = paddle.to_tensor(np.full((20000,), 0.3, "float32"))
        b = paddle.bernoulli(p).numpy()
        assert set(np.unique(b)) <= {0.0, 1.0}
        assert abs(b.mean() - 0.3) < 0.02
        lam = paddle.to_tensor(np.full((20000,), 4.0, "float32"))
        po = paddle.poisson(lam).numpy()
        assert abs(po.mean() - 4.0) < 0.1
        assert abs(po.var() - 4.0) < 0.3
        n = paddle.to_tensor(np.full((20000,), 10, "int32"))
        pr = paddle.to_tensor(np.full((20000,), 0.25, "float32"))
        bi = paddle.binomial(n, pr).numpy()
        assert abs(bi.mean() - 2.5) < 0.05
        assert bi.min() >= 0 and bi.max() <= 10

    def test_gaussian_normal_standard(self):
        paddle.seed(4)
        g = paddle.gaussian([20000], mean=1.0, std=2.0).numpy()
        assert abs(g.mean() - 1.0) < 0.06 and abs(g.std() - 2.0) < 0.06
        s = paddle.standard_normal([20000]).numpy()
        assert abs(s.mean()) < 0.04 and abs(s.std() - 1.0) < 0.04
        n = paddle.normal(mean=-2.0, std=0.5, shape=[20000]).numpy()
        assert abs(n.mean() + 2.0) < 0.02 and abs(n.std() - 0.5) < 0.02

    def test_multinomial_distribution(self):
        paddle.seed(6)
        probs = paddle.to_tensor(np.array([0.1, 0.2, 0.7], "float32"))
        draws = paddle.multinomial(probs, num_samples=10000,
                                   replacement=True).numpy()
        freq = np.bincount(draws, minlength=3) / 10000
        np.testing.assert_allclose(freq, [0.1, 0.2, 0.7], atol=0.03)

    def test_exponential_(self):
        paddle.seed(8)
        x = paddle.to_tensor(np.zeros(20000, "float32"))
        x.exponential_(lam=2.0)
        v = x.numpy()
        assert v.min() >= 0
        assert abs(v.mean() - 0.5) < 0.02
