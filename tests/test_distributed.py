"""Distributed stack tests on the virtual 8-device CPU mesh (SURVEY.md §4:
replaces the reference's 2-GPU-gated harness with
xla_force_host_platform_device_count)."""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn


from paddle_tpu.core.device import local_devices
from paddle_tpu.distributed.spmd import shard_map

needs8 = pytest.mark.skipif(len(local_devices()) < 8, reason="needs 8 devices")


@pytest.fixture()
def hcg_2x2x2():
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
                               "sharding_degree": 1}
    fleet.fleet.init(is_collective=True, strategy=strategy)
    return fleet.fleet.get_hybrid_communicate_group()


class TestTopology:
    def test_communicate_topology(self):
        from paddle_tpu.distributed import CommunicateTopology
        topo = CommunicateTopology(["data", "pipe", "model"], [2, 2, 2])
        assert topo.world_size() == 8
        assert topo.get_rank(data=1, pipe=0, model=1) == 5
        assert topo.get_coord(5) == (1, 0, 1)
        groups = topo.get_comm_list("model")
        assert [0, 1] in groups and [6, 7] in groups
        assert topo.get_axis_list("data", 0) == [0, 1, 2, 3]

    @needs8
    def test_hcg_mesh(self):
        from paddle_tpu.distributed import HybridCommunicateGroup
        hcg = HybridCommunicateGroup(dp_degree=2, mp_degree=2, pp_degree=2)
        mesh = hcg.mesh
        assert mesh.shape["data"] == 2
        assert mesh.shape["model"] == 2
        assert mesh.shape["pipe"] == 2
        assert hcg.get_parallel_mode() == "PipelineParallel"


class TestCollectives:
    """Collective semantics inside shard_map vs numpy oracle (reference:
    test_collective_base.py pattern)."""

    @needs8
    def test_allreduce_allgather(self):
        import paddle_tpu.distributed as dist
        mesh = Mesh(np.array(local_devices()[:4]), ("x",))
        g = dist.Group(ranks=[0, 1, 2, 3], axis_name="x")
        data = np.arange(8, dtype="float32").reshape(4, 2)

        def body(x):
            s = dist.all_reduce(jnp.squeeze(x, 0), group=g)
            gathered = dist.all_gather(None, jnp.squeeze(x, 0), group=g)
            return s[None], gathered[None]
        f = jax.jit(shard_map(body, mesh=mesh, in_specs=P("x"),
                                  out_specs=(P("x"), P("x"))))
        s, gathered = f(jnp.asarray(data))
        np.testing.assert_allclose(np.asarray(s)[0], data.sum(0))
        np.testing.assert_allclose(np.asarray(gathered).reshape(4, 4, 2)[0], data)

    @needs8
    def test_alltoall_and_reduce_scatter(self):
        import paddle_tpu.distributed as dist
        mesh = Mesh(np.array(local_devices()[:4]), ("x",))
        g = dist.Group(ranks=[0, 1, 2, 3], axis_name="x")
        data = np.arange(16, dtype="float32").reshape(4, 4)

        def body(x):
            out = dist.alltoall(jnp.squeeze(x, 0)[:, None], group=g)
            rs = dist.reduce_scatter(None, input_tensor=jnp.squeeze(x, 0), group=g)
            return out.reshape(1, 4), rs[None]
        f = jax.jit(shard_map(body, mesh=mesh, in_specs=P("x"),
                                  out_specs=(P("x"), P("x"))))
        out, rs = f(jnp.asarray(data))
        np.testing.assert_allclose(np.asarray(out), data.T)  # alltoall == transpose
        np.testing.assert_allclose(np.asarray(rs).reshape(-1), data.sum(0))

    @needs8
    def test_send_recv_ppermute(self):
        import paddle_tpu.distributed as dist
        mesh = Mesh(np.array(local_devices()[:4]), ("x",))
        data = np.arange(4, dtype="float32").reshape(4, 1)

        def body(x):
            shifted = jax.lax.ppermute(jnp.squeeze(x, 0), "x",
                                       [(i, (i + 1) % 4) for i in range(4)])
            return shifted[None]
        f = jax.jit(shard_map(body, mesh=mesh, in_specs=P("x"), out_specs=P("x")))
        out = f(jnp.asarray(data))
        np.testing.assert_allclose(np.asarray(out).reshape(-1), [3, 0, 1, 2])

    @needs8
    def test_allreduce_prod_signs_and_zeros(self):
        """ReduceOp.PROD regression: exp(psum(log(t))) NaN'd on any
        non-positive entry; the log-abs + sign-parity + any-zero
        decomposition must match the numpy product exactly in sign and
        to fp tolerance in magnitude."""
        import paddle_tpu.distributed as dist
        mesh = Mesh(np.array(local_devices()[:4]), ("x",))
        g = dist.Group(ranks=[0, 1, 2, 3], axis_name="x")

        def body(x):
            return dist.all_reduce(jnp.squeeze(x, 0),
                                   op=dist.ReduceOp.PROD, group=g)[None]
        f = jax.jit(shard_map(body, mesh=mesh, in_specs=P("x"),
                              out_specs=P("x")))
        cases = [
            np.array([[2.0, 1.0], [-3.0, 2.0], [0.5, -4.0], [-1.0, 0.5]],
                     np.float32),                       # mixed signs
            np.array([[2.0, 1.0], [-3.0, 0.0], [0.0, -4.0], [-1.0, 3.0]],
                     np.float32),                       # zeros -> exactly 0
        ]
        for data in cases:
            out = np.asarray(f(jnp.asarray(data)))
            expect = data.prod(axis=0)
            np.testing.assert_allclose(out[0], expect, rtol=1e-5, atol=0.0)
            for r in range(4):
                np.testing.assert_allclose(out[r], expect, rtol=1e-5,
                                           atol=0.0)
        # integer dtype: exp(Σlog) lands at 41.99999…; the result must be
        # ROUNDED back to the exact product, not truncated to 41
        idata = np.array([[2], [3], [7], [1]], np.int32)
        iout = np.asarray(f(jnp.asarray(idata)))
        np.testing.assert_array_equal(iout.ravel(), [42, 42, 42, 42])

    def test_solo_group_identity(self):
        import paddle_tpu.distributed as dist
        g = dist.Group(ranks=[0], axis_name="solo")
        t = paddle.to_tensor([1.0, 2.0])
        assert dist.all_reduce(t, group=g) is t
        out = []
        dist.all_gather(out, t, group=g)
        assert len(out) == 1


class TestTPLayers:
    @needs8
    def test_column_row_parity_with_dense(self):
        """TP MLP inside shard_map must match the dense computation
        (reference: test_parallel_dygraph_mp_layers.py oracle)."""
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.topology import (HybridCommunicateGroup,
                                                     set_hybrid_communicate_group)
        hcg = HybridCommunicateGroup(dp_degree=1, mp_degree=4, pp_degree=1)
        set_hybrid_communicate_group(hcg)
        mesh = hcg.mesh
        from paddle_tpu.distributed.fleet.meta_parallel import (
            ColumnParallelLinear, RowParallelLinear)
        paddle.seed(0)
        col = ColumnParallelLinear(8, 16, gather_output=False, has_bias=True)
        row = RowParallelLinear(16, 8, input_is_parallel=True, has_bias=True)
        x = np.random.randn(4, 8).astype("float32")
        wc, bc = col.weight.numpy(), col.bias.numpy()
        wr, br = row.weight.numpy(), row.bias.numpy()
        dense = (x @ wc + bc) @ wr + br

        def body(xx, wc_, bc_, wr_, br_):
            h = xx @ wc_ + bc_
            out = h @ wr_
            out = jax.lax.psum(out, "model")
            return out + br_
        f = jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(None, "model"), P("model"), P("model"), P()),
            out_specs=P()))
        out = f(jnp.asarray(x), jnp.asarray(wc), jnp.asarray(bc), jnp.asarray(wr),
                jnp.asarray(br))
        np.testing.assert_allclose(np.asarray(out), dense, rtol=1e-4, atol=1e-4)


class TestSPMDStep:
    @needs8
    def test_dp_loss_matches_serial(self):
        """DP over the mesh must match single-device training (loss-parity
        oracle, test_dist_base.py:1457)."""
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import fleet
        from paddle_tpu.optimizer import SGD

        x = np.random.randn(16, 10).astype("float32")
        y = np.random.randint(0, 4, 16)

        def build(dp):
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": 1,
                                       "pp_degree": 1, "sharding_degree": 1}
            fleet.fleet.init(is_collective=True, strategy=strategy)
            hcg = fleet.fleet.get_hybrid_communicate_group()
            paddle.seed(7)
            net = nn.Sequential(nn.Linear(10, 16), nn.Tanh(), nn.Linear(16, 4))
            opt = SGD(0.1, parameters=net.parameters())
            step, state, _ = dist.make_spmd_train_step(net, nn.CrossEntropyLoss(),
                                                       opt, hcg)
            losses = []
            for i in range(4):
                state, loss = step(state, jax.random.key(0), np.float32(0.1),
                                   [jnp.asarray(x)], [jnp.asarray(y)])
                losses.append(float(loss))
            return losses

        serial = build(1)
        dp4 = build(4)
        np.testing.assert_allclose(serial, dp4, rtol=1e-5, atol=1e-6)

    @needs8
    def test_pipeline_matches_serial_gpt(self):
        """pp2 stacked pipeline loss == serial loss for the same weights."""
        from paddle_tpu.distributed import fleet
        from paddle_tpu.models.gpt import GPTConfig, GPTModel, make_gpt_train_step
        from paddle_tpu.optimizer import SGD

        x = np.random.RandomState(0).randint(0, 128, (4, 16))
        y = np.random.RandomState(1).randint(0, 128, (4, 16))

        def run(pp):
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                       "pp_degree": pp, "sharding_degree": 1}
            fleet.fleet.init(is_collective=True, strategy=strategy)
            hcg = fleet.fleet.get_hybrid_communicate_group()
            paddle.seed(3)
            cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                            num_attention_heads=2, max_position_embeddings=32,
                            compute_dtype="float32")
            model = GPTModel(cfg)
            opt = SGD(0.1)
            step, state = make_gpt_train_step(model, opt, hcg, n_microbatches=2,
                                              remat=False)
            losses = []
            for i in range(3):
                state, loss = step(state, jax.random.key(0), np.float32(0.1),
                                   jnp.asarray(x), jnp.asarray(y))
                losses.append(float(loss))
            return losses

        serial = run(1)
        pp2 = run(2)
        np.testing.assert_allclose(serial, pp2, rtol=1e-4, atol=1e-5)


def test_graft_entry_runs():
    import __graft_entry__ as g
    fn, args = g.entry()
    out = fn(*args)
    assert out.shape[0] == args[0].shape[0]


@needs8
@pytest.mark.slow  # 114s (r4 --durations): the driver runs it separately too
def test_graft_dryrun():
    import __graft_entry__ as g
    g.dryrun_multichip(8)


@needs8
def test_pipeline_jaxpr_flat_in_microbatches():
    """The scan-tick pipeline must have a constant-size jaxpr as M grows
    (round-1 unrolled reduce grew linearly — compile blowup at M=32+)."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models.gpt import GPTConfig, GPTModel, make_gpt_train_step
    from paddle_tpu.optimizer import SGD

    def jaxpr_len(M):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 2, "sharding_degree": 1}
        fleet.fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.fleet.get_hybrid_communicate_group()
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_attention_heads=2, max_position_embeddings=32,
                        compute_dtype="float32")
        model = GPTModel(cfg)
        step, state = make_gpt_train_step(model, SGD(0.1), hcg,
                                          n_microbatches=M, remat=False)
        B = M * 2
        x = jnp.zeros((B, 16), jnp.int32)
        jaxpr = jax.make_jaxpr(
            lambda s, k, lr, a, b: step(s, k, lr, a, b))(
                state, jax.random.key(0), np.float32(0.1), x, x)
        return len(str(jaxpr))

    small, large = jaxpr_len(4), jaxpr_len(32)
    assert large < small * 1.3, (small, large)


@needs8
def test_pipeline_bubble_fraction_is_structural():
    """The scan-tick pipeline runs exactly M+S-1 ticks — the bubble fraction
    (S-1)/(M+S-1) is a structural property of the schedule, the same bound as
    the reference's 1F1B (section_worker.cc:62-137).  Assert the scan trip
    count in the traced program so a schedule regression (extra ticks) is
    caught without hardware timing."""
    import re
    from paddle_tpu.distributed.spmd import spmd_pipeline
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    S, M = 4, 12
    devices = np.array(jax.devices()[:S]).reshape(S)
    mesh = Mesh(devices, ("pipe",))

    def stage_fn(sp, x, i):
        return x * sp

    sparams = jnp.arange(1.0, S + 1.0)
    mb = jnp.ones((M, 2, 4))

    def run(sp, mbs):
        return spmd_pipeline(stage_fn, sp, mbs, S, axis="pipe")

    fn = shard_map(run, mesh=mesh, in_specs=(P("pipe"), P(None)),
                       out_specs=P(None), axis_names={"pipe"})
    jaxpr = jax.make_jaxpr(fn)(sparams, mb)
    # one while/scan with trip count M+S-1: find `length=15` style binding
    text = str(jaxpr)
    counts = [int(m) for m in re.findall(r"length=(\d+)", text)]
    assert (M + S - 1) in counts, (counts, M + S - 1)
    # and the outputs really are the M finished micro-batches
    out = fn(sparams, mb)
    assert out.shape == (M, 2, 4)
    np.testing.assert_allclose(np.asarray(out),
                               np.full((M, 2, 4), 24.0), rtol=1e-6)


@needs8
def test_pipeline_interleaved_matches_serial():
    """Interleaved (virtual-pipeline) schedule: S=2 devices x V=2 chunks must
    reproduce the serial composition of the 4 global stages, and the scan
    must run exactly M*V + S - 1 chunk-slots — the structural form of the
    reference's virtual_pipeline_degree bubble reduction
    (pipeline_parallel.py interleaved 1F1B)."""
    import re
    from paddle_tpu.distributed.spmd import spmd_pipeline_interleaved
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    S, V, M = 2, 2, 4
    devices = np.array(jax.devices()[:S]).reshape(S)
    mesh = Mesh(devices, ("pipe",))

    # global stage g = v*S + d applies x -> x * (g+1) + g
    def chunk_fn(chp, x, m, v):
        return x * chp[0] + chp[1]

    # device d holds chunks [v, :] = (scale, shift) for g = v*S+d
    g_of = lambda d: np.array([[v * S + d + 1.0, v * S + d] for v in range(V)])
    chunk_params = jnp.stack([jnp.asarray(g_of(d)) for d in range(S)])  # [S,V,2]
    mbs = jnp.arange(M * 8.0).reshape(M, 2, 4)

    def run(cp, m):
        local = cp.reshape(cp.shape[1:])  # [1,V,2] -> [V,2]
        return spmd_pipeline_interleaved(
            lambda chp, x, mi, v: chunk_fn(chp, x, mi, v), local, m, S, V,
            axis="pipe")

    fn = shard_map(run, mesh=mesh, in_specs=(P("pipe"), P(None)),
                       out_specs=P(None), axis_names={"pipe"})
    out = fn(chunk_params, mbs)

    expect = np.asarray(mbs)
    for g in range(S * V):
        expect = expect * (g + 1) + g
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)

    text = str(jax.make_jaxpr(fn)(chunk_params, mbs))
    counts = [int(x) for x in re.findall(r"length=(\d+)", text)]
    assert (M * V + S - 1) in counts, (counts, M * V + S - 1)


@needs8
def test_pipeline_interleaved_train_matches_serial_gpt():
    """End-to-end: GPT train losses under pp=2 x virtual_pp=2 match the
    single-device serial run (grads flow correctly through the interleaved
    schedule, including the chunk-major param re-layout)."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models.gpt import GPTConfig, GPTModel, make_gpt_train_step
    from paddle_tpu.optimizer import SGD

    x = np.random.RandomState(0).randint(0, 128, (4, 16))
    y = np.random.RandomState(1).randint(0, 128, (4, 16))

    def run(pp, vpp):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": pp, "sharding_degree": 1}
        fleet.fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.fleet.get_hybrid_communicate_group()
        paddle.seed(3)
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                        num_attention_heads=2, max_position_embeddings=32,
                        compute_dtype="float32")
        model = GPTModel(cfg)
        step, state = make_gpt_train_step(model, SGD(0.1), hcg,
                                          n_microbatches=2, remat=False,
                                          virtual_pp_degree=vpp)
        losses = []
        for _ in range(3):
            state, loss = step(state, jax.random.key(0), np.float32(0.1),
                               jnp.asarray(x), jnp.asarray(y))
            losses.append(float(loss))
        return losses

    serial = run(1, 1)
    vpp2 = run(2, 2)
    np.testing.assert_allclose(serial, vpp2, rtol=1e-4, atol=1e-5)


def test_virtual_pp_degree_flows_from_strategy():
    """hybrid_configs["pp_configs"]["virtual_pipeline_degree"] reaches the
    HCG (≙ reference pp_configs / num_virtual_pipeline_stages plumbing)."""
    from paddle_tpu.distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "pp_degree": 1,
                               "pp_configs": {"virtual_pipeline_degree": 2}}
    fleet.fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.fleet.get_hybrid_communicate_group()
    assert hcg.get_virtual_pipeline_degree() == 2


@needs8
@pytest.mark.parametrize("S,V,M", [(s, v, m * s)
                                   for s in (2, 3, 4)
                                   for v in (2, 3)
                                   for m in (1, 2)])
def test_pipeline_interleaved_sweep(S, V, M):
    """Exhaustive small-grid (S,V,M) parity sweep (VERDICT r3 weak #7): the
    clipped-decode safety claim ('inactive slots' outputs are never selected
    by an active receiver') must hold for every schedule shape, not just the
    one S=2,V=2,M=4 point.  Each combo checks: (a) outputs equal the serial
    composition of the S*V global affine stages, (b) the scan is exactly
    M*V+S-1 chunk-slots (structural bubble), (c) gradients through the
    schedule match the serial function's."""
    import re
    from paddle_tpu.distributed.spmd import spmd_pipeline_interleaved

    devices = np.array(jax.devices()[:S]).reshape(S)
    mesh = Mesh(devices, ("pipe",))

    # global stage g = v*S + d applies x -> x * (g+1) + g
    g_of = lambda d: np.array([[v * S + d + 1.0, v * S + d] for v in range(V)])
    chunk_params = jnp.stack([jnp.asarray(g_of(d)) for d in range(S)])
    mbs = jnp.arange(M * 4.0).reshape(M, 2, 2) / (M * 4.0)

    def run(cp, m):
        local = cp.reshape(cp.shape[1:])
        return spmd_pipeline_interleaved(
            lambda chp, x, mi, v: x * chp[0] + chp[1], local, m, S, V,
            axis="pipe")

    fn = shard_map(run, mesh=mesh, in_specs=(P("pipe"), P(None)),
                       out_specs=P(None), axis_names={"pipe"})
    out = fn(chunk_params, mbs)
    expect = np.asarray(mbs)
    for g in range(S * V):
        expect = expect * (g + 1) + g
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5)

    text = str(jax.make_jaxpr(fn)(chunk_params, mbs))
    counts = [int(x) for x in re.findall(r"length=(\d+)", text)]
    assert (M * V + S - 1) in counts, (counts, M * V + S - 1)

    # gradients: d(sum(out))/d(mbs) of the schedule == serial product of
    # the scales (each stage is affine, so the grad is prod(g+1) everywhere)
    g = jax.grad(lambda m: jnp.sum(fn(chunk_params, m)))(mbs)
    scale = float(np.prod(np.arange(1, S * V + 1)))
    np.testing.assert_allclose(np.asarray(g), np.full(g.shape, scale),
                               rtol=1e-5)


@needs8
@pytest.mark.parametrize("S,V,M", [(2, 2, 3), (2, 2, 5), (4, 2, 6),
                                   (3, 3, 4)])
def test_pipeline_interleaved_rejects_bad_M(S, V, M):
    """The M % S == 0 constraint (same as Megatron's) raises cleanly for
    every non-multiple, before any tracing."""
    from paddle_tpu.distributed.spmd import spmd_pipeline_interleaved
    with pytest.raises(ValueError, match="multiple of the pipeline"):
        spmd_pipeline_interleaved(
            lambda chp, x, mi, v: x, jnp.zeros((V, 2)),
            jnp.zeros((M, 2, 2)), S, V, axis="pipe")


def test_interleave_layers_roundtrip():
    """Chunk-interleaved storage permutation and its inverse; position
    d*(V*lpc)+v*lpc+i must hold original layer (v*S+d)*lpc+i."""
    from paddle_tpu.distributed.pipeline_engine import (deinterleave_layers,
                                                        interleave_layers)
    S, V, lpc = 2, 3, 2
    L = S * V * lpc
    x = jnp.arange(L * 4.0).reshape(L, 4)
    y = interleave_layers(x, S, V)
    for d in range(S):
        for v in range(V):
            for i in range(lpc):
                np.testing.assert_array_equal(
                    np.asarray(y[d * V * lpc + v * lpc + i]),
                    np.asarray(x[(v * S + d) * lpc + i]))
    np.testing.assert_array_equal(np.asarray(deinterleave_layers(y, S, V)),
                                  np.asarray(x))


@needs8
def test_pipeline_interleaved_with_mp_matches_serial():
    """3-axis: pp=2 x vpp=2 x mp=2 must reproduce the serial run — the
    interleaved schedule composes with GSPMD tensor parallelism inside the
    chunk bodies (dp axis covered by the dryrun)."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models.gpt import GPTConfig, GPTModel, make_gpt_train_step
    from paddle_tpu.optimizer import SGD

    x = np.random.RandomState(20).randint(0, 128, (4, 16))
    y = np.random.RandomState(21).randint(0, 128, (4, 16))

    def run(pp, vpp, mp):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {
            "dp_degree": 1, "mp_degree": mp, "pp_degree": pp,
            "sharding_degree": 1,
            "pp_configs": {"virtual_pipeline_degree": vpp}}
        fleet.fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.fleet.get_hybrid_communicate_group()
        paddle.seed(5)
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                        num_attention_heads=2, max_position_embeddings=32,
                        compute_dtype="float32")
        model = GPTModel(cfg)
        step, state = make_gpt_train_step(model, SGD(0.1), hcg,
                                          n_microbatches=2, remat=False)
        losses = []
        for _ in range(2):
            state, loss = step(state, jax.random.key(0), np.float32(0.1),
                               jnp.asarray(x), jnp.asarray(y))
            losses.append(float(loss))
        return losses

    serial = run(1, 1, 1)
    hybrid = run(2, 2, 2)
    np.testing.assert_allclose(serial, hybrid, rtol=1e-4, atol=1e-5)
