"""KV-cache autoregressive generation (models/gpt.py generate/prefill/
decode_step) vs the no-cache oracle: re-running the full forward on the
growing sequence.  ≙ the reference ecosystem's generation_utils greedy/
sampling contracts + fused_multi_transformer CacheKV semantics."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTConfig, GPTModel


@pytest.fixture(scope="module")
def model_and_params():
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=3,
                    num_attention_heads=4, max_position_embeddings=64,
                    compute_dtype="float32")
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    return model, params


def _oracle_greedy(model, params, prompt, n):
    """No-cache decoding: full forward over the growing sequence."""
    ids = np.asarray(prompt)
    out = []
    for _ in range(n):
        h = model.embed_fn(params, jnp.asarray(ids))
        h = model.scan_blocks(params, h, remat=False)
        logits = model.head_fn(params, h)
        nxt = np.asarray(jnp.argmax(logits[:, -1, :], -1)).astype(np.int64)
        out.append(nxt)
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
    return np.stack(out, axis=1)


class TestGenerate:
    def test_greedy_matches_no_cache_oracle(self, model_and_params):
        model, params = model_and_params
        prompt = np.random.RandomState(0).randint(0, 97, (2, 5))
        want = _oracle_greedy(model, params, prompt, 8)
        got = model.generate(params, prompt, max_new_tokens=8)
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_decode_logits_match_full_forward(self, model_and_params):
        """Cache-path hidden state at position t equals the full-forward
        hidden state at t (the cache IS the attention state, not an
        approximation)."""
        model, params = model_and_params
        ids = np.random.RandomState(1).randint(0, 97, (2, 6))
        max_len = 8

        h_pre, caches = model.prefill(params, jnp.asarray(ids), max_len)
        # feed the true next token (from data, not sampling) through decode
        tok = jnp.asarray(np.random.RandomState(2).randint(0, 97, (2,)))
        dt = jnp.dtype(model.config.compute_dtype)
        h1 = (jnp.take(params["wte"], tok[:, None], axis=0)
              + params["wpe"][6][None, None, :]).astype(dt)
        h1, _ = model.decode_step(params, h1, caches, jnp.asarray(6))

        full = jnp.concatenate([jnp.asarray(ids), tok[:, None]], axis=1)
        hf = model.scan_blocks(params, model.embed_fn(params, full),
                               remat=False)
        np.testing.assert_allclose(np.asarray(h1[:, 0]), np.asarray(hf[:, -1]),
                                   rtol=2e-4, atol=2e-5)
        # prefill hidden states equal full-forward prefix states too
        np.testing.assert_allclose(np.asarray(h_pre), np.asarray(hf[:, :6]),
                                   rtol=2e-4, atol=2e-5)

    def test_single_token_and_cap(self, model_and_params):
        model, params = model_and_params
        prompt = np.zeros((1, 3), np.int64)
        out = model.generate(params, prompt, max_new_tokens=1)
        assert out.shape == (1, 1)
        with pytest.raises(ValueError, match="max_position_embeddings"):
            model.generate(params, prompt, max_new_tokens=62)

    def test_sampling_deterministic_under_key(self, model_and_params):
        model, params = model_and_params
        prompt = np.random.RandomState(3).randint(0, 97, (2, 4))
        k = jax.random.key(42)
        a = model.generate(params, prompt, max_new_tokens=6, greedy=False,
                           temperature=0.8, top_k=10, key=k)
        b = model.generate(params, prompt, max_new_tokens=6, greedy=False,
                           temperature=0.8, top_k=10, key=k)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.shape == (2, 6)
        assert np.all((np.asarray(a) >= 0) & (np.asarray(a) < 97))

    def test_sampling_requires_key(self, model_and_params):
        model, params = model_and_params
        with pytest.raises(ValueError, match="requires key"):
            model.generate(params, np.zeros((1, 2), np.int64), 2, greedy=False)

    def test_top_p_tiny_nucleus_equals_greedy(self, model_and_params):
        """top_p small enough that only the argmax token survives the
        nucleus ⇒ sampling must reproduce the greedy sequence exactly."""
        model, params = model_and_params
        prompt = np.random.RandomState(12).randint(0, 97, (2, 4))
        greedy = model.generate(params, prompt, max_new_tokens=5)
        nucl = model.generate(params, prompt, max_new_tokens=5, greedy=False,
                              top_p=1e-6, key=jax.random.key(0))
        np.testing.assert_array_equal(np.asarray(nucl), np.asarray(greedy))

    def test_top_p_validation(self, model_and_params):
        model, params = model_and_params
        with pytest.raises(ValueError, match="top_p"):
            model.generate(params, np.zeros((1, 2), np.int64), 2,
                           greedy=False, top_p=1.5, key=jax.random.key(0))


class TestProgramCache:
    def test_repeat_calls_reuse_compiled_program(self, model_and_params):
        model, params = model_and_params
        prompt = np.zeros((1, 4), np.int64)
        a = model.generate(params, prompt, max_new_tokens=3)
        r1 = model._gen_program(4, 3, 1.0, None, None, True)
        b = model.generate(params, prompt, max_new_tokens=3)
        r2 = model._gen_program(4, 3, 1.0, None, None, True)
        assert r1 is r2                       # same memoized jitted program
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_zero_tokens_returns_empty(self, model_and_params):
        model, params = model_and_params
        out = model.generate(params, np.zeros((2, 3), np.int64), 0)
        assert out.shape == (2, 0)


def _beam_oracle(model, params, prompt, n, K):
    """Brute-force beam search via full re-forward (no cache), numpy."""
    B = prompt.shape[0]
    assert B == 1
    seqs = [list()]
    h = model.scan_blocks(params, model.embed_fn(params, jnp.asarray(prompt)),
                          remat=False)
    lp0 = np.asarray(jax.nn.log_softmax(
        model.head_fn(params, h)[:, -1].astype(jnp.float32), -1))[0]
    order = np.argsort(-lp0)[:K]
    beams = [([int(t)], float(lp0[t])) for t in order]
    for _ in range(n - 1):
        cand = []
        for toks, score in beams:
            ids = np.concatenate([prompt[0], np.asarray(toks)])[None]
            h = model.scan_blocks(params,
                                  model.embed_fn(params, jnp.asarray(ids)),
                                  remat=False)
            lp = np.asarray(jax.nn.log_softmax(
                model.head_fn(params, h)[:, -1].astype(jnp.float32), -1))[0]
            for t in np.argsort(-lp)[:K]:
                cand.append((toks + [int(t)], score + float(lp[t])))
        cand.sort(key=lambda x: -x[1])
        beams = cand[:K]
    return beams[0]


class TestBeamSearch:
    def test_matches_bruteforce_oracle(self, model_and_params):
        model, params = model_and_params
        prompt = np.random.RandomState(8).randint(0, 97, (1, 5))
        want_toks, want_score = _beam_oracle(model, params, prompt, 4, 3)
        seq, score = model.generate_beam(params, prompt, max_new_tokens=4,
                                         num_beams=3)
        np.testing.assert_array_equal(np.asarray(seq)[0], want_toks)
        np.testing.assert_allclose(float(score[0]), want_score / 4.0,
                                   rtol=1e-4)

    def test_single_beam_equals_greedy(self, model_and_params):
        model, params = model_and_params
        prompt = np.random.RandomState(9).randint(0, 97, (2, 4))
        greedy = model.generate(params, prompt, max_new_tokens=5)
        beam, _ = model.generate_beam(params, prompt, max_new_tokens=5,
                                      num_beams=1)
        np.testing.assert_array_equal(np.asarray(beam), np.asarray(greedy))

    def test_eos_freezes_beam(self, model_and_params):
        """length_penalty=0 ⇒ raw cum log-prob scores: a beam that finishes
        at step 0 (EOS = the argmax token) strictly beats any beam that keeps
        accumulating negative log-probs, so the winner MUST be the frozen
        all-EOS sequence — non-vacuous by construction."""
        model, params = model_and_params
        prompt = np.random.RandomState(10).randint(0, 97, (1, 4))
        first = int(np.asarray(model.generate(params, prompt, 1))[0, 0])
        seq, score = model.generate_beam(params, prompt, max_new_tokens=6,
                                         num_beams=2, eos_token_id=first,
                                         length_penalty=0.0)
        s = np.asarray(seq)[0]
        np.testing.assert_array_equal(s, np.full(6, first))
        assert float(score[0]) < 0.0  # exactly the one-token log-prob

    def test_length_penalty_uses_finish_length(self, model_and_params):
        """Scores divide by each beam's TRUE hypothesis length (1 for a
        step-0 EOS finish), not by max_new_tokens.  Under penalty=1.0 the
        length-6 beam's mean log-prob beats the single-token beam's full
        log-prob here, so the ranking flips vs penalty=0 — under the old
        fixed-length bug the EOS beam's score would be cum/6 and it would
        (wrongly) win both times."""
        model, params = model_and_params
        prompt = np.random.RandomState(11).randint(0, 97, (1, 4))
        first = int(np.asarray(model.generate(params, prompt, 1))[0, 0])
        seq0, s0 = model.generate_beam(params, prompt, max_new_tokens=6,
                                       num_beams=2, eos_token_id=first,
                                       length_penalty=0.0)
        seq1, s1 = model.generate_beam(params, prompt, max_new_tokens=6,
                                       num_beams=2, eos_token_id=first,
                                       length_penalty=1.0)
        # penalty=0 winner: the frozen all-EOS beam (raw cum favors short)
        np.testing.assert_array_equal(np.asarray(seq0)[0], np.full(6, first))
        # penalty=1 winner: a real length-6 continuation, scored as cum/6 —
        # its score must beat the EOS beam's cum/1 (= s0, since 1**p == 1)
        assert not np.all(np.asarray(seq1)[0] == first)
        assert float(s1[0]) > float(s0[0])


class TestMoEGenerate:
    @pytest.fixture(scope="class")
    def moe_pair(self):
        from paddle_tpu.models.ernie_moe import ErnieMoeConfig, ErnieMoeModel

        paddle.seed(13)
        cfg = ErnieMoeConfig(vocab_size=89, hidden_size=32, num_layers=2,
                             num_attention_heads=4, num_experts=4, top_k=2,
                             max_position_embeddings=48,
                             compute_dtype="float32")
        model = ErnieMoeModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        return model, params

    def _oracle_greedy(self, model, params, prompt, n):
        """Full re-forward each step with the SAME no-drop routing the
        decode path uses (capacity dropping is context-dependent, so parity
        requires the no-drop inference capacity on both sides)."""
        ids = np.asarray(prompt)
        out = []
        for _ in range(n):
            # model.prefill IS a full no-drop forward over the sequence
            h, _ = model.prefill(params, jnp.asarray(ids), ids.shape[1])
            logits = model._head_logits(params, h, dtype=jnp.float32)
            nxt = np.asarray(jnp.argmax(logits[:, -1, :], -1)).astype(np.int64)
            out.append(nxt)
            ids = np.concatenate([ids, nxt[:, None]], axis=1)
        return np.stack(out, axis=1)

    def test_greedy_matches_full_forward(self, moe_pair):
        model, params = moe_pair
        prompt = np.random.RandomState(14).randint(0, 89, (2, 5))
        want = self._oracle_greedy(model, params, prompt, 6)
        got = model.generate(params, prompt, max_new_tokens=6)
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_decode_hidden_matches_prefill(self, moe_pair):
        """Incremental MoE decode at position t == full no-drop forward at
        t — routing decisions for a single token reproduce the full-context
        ones because nothing is capacity-dropped."""
        model, params = moe_pair
        ids = np.random.RandomState(15).randint(0, 89, (2, 7))
        _, caches = model.prefill(params, jnp.asarray(ids[:, :6]), 12)
        tok = jnp.asarray(ids[:, 6])
        dt = jnp.dtype(model.config.compute_dtype)
        h = (jnp.take(params["wte"], tok[:, None], axis=0)
             + params["wpe"][6][None, None, :]).astype(dt)
        h, _ = model.decode_step(params, h, caches, jnp.asarray(6))
        hf, _ = model.prefill(params, jnp.asarray(ids), 7)
        np.testing.assert_allclose(np.asarray(h[:, 0]), np.asarray(hf[:, -1]),
                                   rtol=2e-4, atol=2e-5)

    def test_sampling_shapes_and_determinism(self, moe_pair):
        model, params = moe_pair
        prompt = np.random.RandomState(16).randint(0, 89, (2, 4))
        k = jax.random.key(3)
        a = model.generate(params, prompt, 5, greedy=False, temperature=0.9,
                           top_k=8, key=k)
        b = model.generate(params, prompt, 5, greedy=False, temperature=0.9,
                           top_k=8, key=k)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.shape == (2, 5)


class TestMoEBeam:
    def test_single_beam_equals_greedy_moe(self):
        """The shared CausalDecoderMixin gives ERNIE-MoE beam search for
        free; num_beams=1 must reproduce greedy decoding."""
        from paddle_tpu.models.ernie_moe import ErnieMoeConfig, ErnieMoeModel

        paddle.seed(17)
        cfg = ErnieMoeConfig(vocab_size=61, hidden_size=32, num_layers=2,
                             num_attention_heads=4, num_experts=4, top_k=2,
                             max_position_embeddings=32,
                             compute_dtype="float32")
        model = ErnieMoeModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        prompt = np.random.RandomState(18).randint(0, 61, (2, 4))
        greedy = model.generate(params, prompt, max_new_tokens=4)
        beam, score = model.generate_beam(params, prompt, max_new_tokens=4,
                                          num_beams=1)
        np.testing.assert_array_equal(np.asarray(beam), np.asarray(greedy))
        assert score.shape == (2,)


class TestMaskedPrompts:
    def test_left_padded_equals_unpadded_gpt(self, model_and_params):
        """Ragged prompts served through one bucket: left-pad + prompt_mask
        must reproduce each row's unpadded greedy generation exactly (pad
        keys masked out of attention, positions shifted per row)."""
        model, params = model_and_params
        rs = np.random.RandomState(30)
        P = 8
        lens = [3, 8, 5]
        rows, masks, singles = [], [], []
        for L in lens:
            ids = rs.randint(0, 97, (1, L))
            singles.append(model.generate(params, ids, max_new_tokens=6))
            rows.append(np.concatenate([np.zeros((1, P - L), np.int64), ids],
                                       axis=1))
            masks.append(np.concatenate([np.zeros((1, P - L), np.int32),
                                         np.ones((1, L), np.int32)], axis=1))
        batch = np.concatenate(rows)
        mask = np.concatenate(masks)
        got = model.generate(params, batch, max_new_tokens=6,
                             prompt_mask=mask)
        for i, single in enumerate(singles):
            np.testing.assert_array_equal(np.asarray(got)[i],
                                          np.asarray(single)[0],
                                          err_msg=f"row {i} len {lens[i]}")

    def test_left_padded_equals_unpadded_moe(self):
        from paddle_tpu.models.ernie_moe import ErnieMoeConfig, ErnieMoeModel

        paddle.seed(19)
        cfg = ErnieMoeConfig(vocab_size=71, hidden_size=32, num_layers=2,
                             num_attention_heads=4, num_experts=4, top_k=2,
                             max_position_embeddings=32,
                             compute_dtype="float32")
        model = ErnieMoeModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        rs = np.random.RandomState(31)
        ids = rs.randint(0, 71, (1, 4))
        single = model.generate(params, ids, max_new_tokens=4)
        padded = np.concatenate([np.zeros((1, 3), np.int64), ids], axis=1)
        mask = np.concatenate([np.zeros((1, 3), np.int32),
                               np.ones((1, 4), np.int32)], axis=1)
        got = model.generate(params, padded, max_new_tokens=4,
                             prompt_mask=mask)
        np.testing.assert_array_equal(np.asarray(got)[0],
                                      np.asarray(single)[0])

    def test_mask_shares_program_across_pad_lengths(self, model_and_params):
        """pad lengths are traced data: two ragged batches with the same
        bucket shape reuse ONE compiled program."""
        model, params = model_and_params
        mask1 = np.array([[0, 0, 1, 1, 1, 1]], np.int32)
        mask2 = np.array([[0, 0, 0, 0, 1, 1]], np.int32)
        ids = np.random.RandomState(32).randint(0, 97, (1, 6))
        model.generate(params, ids, 3, prompt_mask=mask1)
        r1 = model._gen_program(6, 3, 1.0, None, None, True, masked=True)
        model.generate(params, ids, 3, prompt_mask=mask2)
        r2 = model._gen_program(6, 3, 1.0, None, None, True, masked=True)
        assert r1 is r2


class TestMaskValidation:
    def test_right_padded_mask_rejected(self, model_and_params):
        model, params = model_and_params
        ids = np.zeros((1, 5), np.int64)
        with pytest.raises(ValueError, match="LEFT-padded"):
            model.generate(params, ids, 3,
                           prompt_mask=np.array([[1, 1, 1, 0, 0]]))

    def test_all_pad_row_rejected(self, model_and_params):
        model, params = model_and_params
        ids = np.zeros((2, 4), np.int64)
        mask = np.array([[0, 0, 1, 1], [0, 0, 0, 0]])
        with pytest.raises(ValueError, match="all-padding"):
            model.generate(params, ids, 3, prompt_mask=mask)

    def test_shape_mismatch_rejected(self, model_and_params):
        model, params = model_and_params
        with pytest.raises(ValueError, match="shape"):
            model.generate(params, np.zeros((1, 5), np.int64), 3,
                           prompt_mask=np.ones((1, 4), np.int32))


class TestExportedProgram:
    def test_save_load_roundtrip(self, model_and_params, tmp_path):
        """The generation loop exports as a StableHLO artifact and a fresh
        load reproduces the live program's tokens exactly (≙ jit.save's
        __model__+params serving contract, for the decode loop)."""
        from paddle_tpu.models._decode import (load_generate_program,
                                               save_generate_program)

        model, params = model_and_params
        prompt = np.random.RandomState(40).randint(0, 97, (2, 5))
        want = model.generate(params, prompt, max_new_tokens=6)

        path = str(tmp_path / "gpt_gen")
        save_generate_program(model, params, path, prompt_len=5,
                              max_new_tokens=6, batch_size=2)
        fn, meta = load_generate_program(path)
        assert meta["max_new_tokens"] == 6
        got = fn(prompt)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_sampled_export_deterministic_per_seed(self, model_and_params,
                                                   tmp_path):
        from paddle_tpu.models._decode import (load_generate_program,
                                               save_generate_program)

        model, params = model_and_params
        path = str(tmp_path / "gpt_gen_s")
        save_generate_program(model, params, path, prompt_len=4,
                              max_new_tokens=5, batch_size=1, greedy=False,
                              temperature=0.9, top_k=12)
        fn, _ = load_generate_program(path)
        prompt = np.random.RandomState(41).randint(0, 97, (1, 4))
        a, b = fn(prompt, seed=7), fn(prompt, seed=7)
        c = fn(prompt, seed=8)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.shape == (1, 5)
        # the seed operand must actually reach the sampler (verified once
        # for these fixed seeds/weights — a baked-in key would tie a == c)
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_masked_export_roundtrip(self, model_and_params, tmp_path):
        """Ragged serving from an artifact: masked=True exports a pad_lens
        operand; the loaded fn reproduces live masked generation."""
        from paddle_tpu.models._decode import (load_generate_program,
                                               save_generate_program)

        model, params = model_and_params
        path = str(tmp_path / "gpt_gen_m")
        save_generate_program(model, params, path, prompt_len=6,
                              max_new_tokens=4, batch_size=1, masked=True)
        fn, meta = load_generate_program(path)
        assert meta["masked"]
        ids = np.random.RandomState(42).randint(0, 97, (1, 4))
        padded = np.concatenate([np.zeros((1, 2), np.int64), ids], axis=1)
        mask = np.array([[0, 0, 1, 1, 1, 1]], np.int32)
        want = model.generate(params, padded, 4, prompt_mask=mask)
        got = fn(padded, prompt_mask=mask)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        with pytest.raises(ValueError, match="pass prompt_mask"):
            fn(padded)

    def test_export_validates_position_bound(self, model_and_params,
                                             tmp_path):
        from paddle_tpu.models._decode import save_generate_program

        model, params = model_and_params
        with pytest.raises(ValueError, match="max_position_embeddings"):
            save_generate_program(model, params, str(tmp_path / "x"),
                                  prompt_len=10, max_new_tokens=200)


class TestPredictorIntegration:
    def test_predictor_serves_generation_artifact(self, model_and_params,
                                                  tmp_path):
        """paddle.inference.Config/Predictor recognizes a .genmodel artifact:
        the reference predictor calling convention (handles + run) serves the
        exported decode loop."""
        from paddle_tpu.inference import Config, create_predictor
        from paddle_tpu.models._decode import save_generate_program

        model, params = model_and_params
        path = str(tmp_path / "served")
        save_generate_program(model, params, path, prompt_len=5,
                              max_new_tokens=4, batch_size=2)
        pred = create_predictor(Config(path))
        assert pred.get_input_names() == ["input_ids", "seed"]

        prompt = np.random.RandomState(50).randint(0, 97, (2, 5))
        pred.get_input_handle("input_ids").copy_from_cpu(
            prompt.astype(np.int32))
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
        want = model.generate(params, prompt, max_new_tokens=4)
        np.testing.assert_array_equal(out, np.asarray(want))

        # clone shares the executable and serves independently
        p2 = pred.clone()
        p2.get_input_handle("input_ids").copy_from_cpu(
            prompt.astype(np.int32))
        p2.run()
        out2 = p2.get_output_handle(p2.get_output_names()[0]).copy_to_cpu()
        np.testing.assert_array_equal(out2, out)

    def test_predictor_serves_masked_artifact(self, model_and_params,
                                              tmp_path):
        from paddle_tpu.inference import Config, create_predictor
        from paddle_tpu.models._decode import save_generate_program

        model, params = model_and_params
        path = str(tmp_path / "served_m")
        save_generate_program(model, params, path, prompt_len=6,
                              max_new_tokens=3, batch_size=1, masked=True)
        pred = create_predictor(Config(path))
        assert pred.get_input_names() == ["input_ids", "seed", "prompt_mask"]
        ids = np.random.RandomState(51).randint(0, 97, (1, 4))
        padded = np.concatenate([np.zeros((1, 2), np.int32),
                                 ids.astype(np.int32)], axis=1)
        mask = np.array([[0, 0, 1, 1, 1, 1]], np.int32)
        pred.get_input_handle("input_ids").copy_from_cpu(padded)
        pred.get_input_handle("prompt_mask").copy_from_cpu(mask)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
        want = model.generate(params, padded, 3, prompt_mask=mask)
        np.testing.assert_array_equal(out, np.asarray(want))

    def test_predictor_missing_model_still_clear_error(self):
        from paddle_tpu.inference import Config, create_predictor
        with pytest.raises(ValueError, match="not found"):
            create_predictor(Config("/nonexistent/prefix"))


class TestSpeculative:
    """Greedy speculative decoding must be LOSSLESS: bit-identical to the
    target's plain greedy generate, for any draft quality."""

    @pytest.fixture(scope="class")
    def draft(self):
        paddle.seed(99)
        cfg = GPTConfig(vocab_size=97, hidden_size=16, num_layers=1,
                        num_attention_heads=2, max_position_embeddings=64,
                        compute_dtype="float32")
        m = GPTModel(cfg)
        return m, {n: p._data for n, p in m.named_parameters()}

    @pytest.mark.parametrize("K", [1, 2, 4])
    def test_lossless_vs_greedy_random_draft(self, model_and_params, draft,
                                             K):
        model, params = model_and_params
        dmodel, dparams = draft
        prompt = np.random.RandomState(60).randint(0, 97, (1, 5))
        want = model.generate(params, prompt, max_new_tokens=9)
        got = model.generate_speculative(params, prompt, 9, dmodel, dparams,
                                         draft_k=K)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want),
                                      err_msg=f"K={K}")

    def test_lossless_with_perfect_draft(self, model_and_params):
        """Draft == target: every round accepts draft_k+1 tokens and the
        output is still exactly greedy."""
        model, params = model_and_params
        prompt = np.random.RandomState(61).randint(0, 97, (1, 4))
        want = model.generate(params, prompt, max_new_tokens=7)
        got = model.generate_speculative(params, prompt, 7, model, params,
                                         draft_k=3)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_single_token_and_validation(self, model_and_params, draft):
        model, params = model_and_params
        dmodel, dparams = draft
        prompt = np.zeros((1, 3), np.int64)
        out = model.generate_speculative(params, prompt, 1, dmodel, dparams)
        want = model.generate(params, prompt, 1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(want))
        with pytest.raises(ValueError, match="max_position_embeddings"):
            model.generate_speculative(params, prompt, 60, dmodel, dparams)

    def test_batched_rows_accept_independently(self, model_and_params,
                                               draft):
        """B=3: every row's speculative output equals that row's solo greedy
        run — per-row acceptance/cache offsets are independent even though
        rows finish their token budgets at different round counts."""
        model, params = model_and_params
        dmodel, dparams = draft
        prompts = np.random.RandomState(62).randint(0, 97, (3, 5))
        got = model.generate_speculative(params, prompts, 8, dmodel, dparams,
                                         draft_k=3)
        for b in range(3):
            solo = model.generate(params, prompts[b:b + 1], 8)
            np.testing.assert_array_equal(np.asarray(got)[b],
                                          np.asarray(solo)[0],
                                          err_msg=f"row {b}")

    def test_vocab_mismatch_rejected(self, model_and_params):
        model, params = model_and_params
        paddle.seed(98)
        other = GPTModel(GPTConfig(vocab_size=50, hidden_size=16,
                                   num_layers=1, num_attention_heads=2,
                                   max_position_embeddings=64,
                                   compute_dtype="float32"))
        oparams = {n: p._data for n, p in other.named_parameters()}
        with pytest.raises(ValueError, match="vocab"):
            model.generate_speculative(params, np.zeros((1, 3), np.int64), 2,
                                       other, oparams)


class TestSpeculativeAcceptMath:
    """The acceptance-rejection core must reproduce the TARGET distribution
    exactly (Leviathan/Chen theorem) — checked empirically on fixed
    distributions with 100k vectorized trials."""

    def test_first_token_marginal_matches_target(self):
        from paddle_tpu.models._decode import speculative_accept

        V, K = 6, 2
        rs = np.random.RandomState(0)
        p = jnp.asarray(rs.dirichlet(np.ones(V), size=K + 1), jnp.float32)
        q = jnp.asarray(rs.dirichlet(np.ones(V), size=K), jnp.float32)

        def one(key):
            kd, ka = jax.random.split(key)
            d = jax.random.categorical(
                kd, jnp.log(q), -1).astype(jnp.int32)     # (K,) from q rows
            lead, repl = speculative_accept(q[None], p[None], d[None], ka)
            return jnp.where(lead[0] >= 1, d[0], repl[0])

        n = 100_000
        toks = jax.vmap(one)(jax.random.split(jax.random.key(1), n))
        freq = np.bincount(np.asarray(toks), minlength=V) / n
        np.testing.assert_allclose(freq, np.asarray(p[0]), atol=0.02)

    def test_perfect_draft_always_accepts_and_uses_bonus(self):
        from paddle_tpu.models._decode import speculative_accept

        V, K = 5, 3
        rs = np.random.RandomState(2)
        p = jnp.asarray(rs.dirichlet(np.ones(V), size=K + 1), jnp.float32)
        q = p[:K]

        def one(key):
            kd, ka = jax.random.split(key)
            d = jax.random.categorical(kd, jnp.log(q), -1).astype(jnp.int32)
            lead, repl = speculative_accept(q[None], p[None], d[None], ka)
            return lead[0], repl[0]

        leads, repls = jax.vmap(one)(jax.random.split(jax.random.key(3),
                                                      20_000))
        assert np.all(np.asarray(leads) == K)             # q == p ⇒ accept
        freq = np.bincount(np.asarray(repls), minlength=V) / 20_000
        np.testing.assert_allclose(freq, np.asarray(p[K]), atol=0.02)

    def test_disjoint_draft_always_rejects_to_residual(self):
        """Draft puts all mass where the target has (almost) none: nothing
        accepts, and the replacement follows the residual ≈ target."""
        from paddle_tpu.models._decode import speculative_accept

        V, K = 4, 1
        p = jnp.asarray([[0.5, 0.5, 0.0, 0.0]] * (K + 1), jnp.float32)
        q = jnp.asarray([[0.0, 0.0, 0.5, 0.5]] * K, jnp.float32)

        def one(key):
            kd, ka = jax.random.split(key)
            d = jax.random.categorical(kd, jnp.log(q + 1e-20), -1) \
                .astype(jnp.int32)
            lead, repl = speculative_accept(q[None], p[None], d[None], ka)
            return lead[0], repl[0]

        leads, repls = jax.vmap(one)(jax.random.split(jax.random.key(4),
                                                      20_000))
        assert np.all(np.asarray(leads) == 0)
        freq = np.bincount(np.asarray(repls), minlength=V) / 20_000
        np.testing.assert_allclose(freq, np.asarray(p[0]), atol=0.02)


class TestSpeculativeSampling:
    """Sampling-mode speculative decoding draws from EXACTLY the target's
    filtered distribution (acceptance-rejection), not the draft's."""

    @pytest.fixture(scope="class")
    def tiny(self):
        paddle.seed(70)
        tcfg = GPTConfig(vocab_size=13, hidden_size=16, num_layers=2,
                         num_attention_heads=2, max_position_embeddings=32,
                         compute_dtype="float32")
        target = GPTModel(tcfg)
        paddle.seed(71)
        dcfg = GPTConfig(vocab_size=13, hidden_size=8, num_layers=1,
                         num_attention_heads=2, max_position_embeddings=32,
                         compute_dtype="float32")
        draft = GPTModel(dcfg)
        return (target, {n: p._data for n, p in target.named_parameters()},
                draft, {n: p._data for n, p in draft.named_parameters()})

    def test_token_marginals_match_plain_sampling(self, tiny):
        """Empirical distribution of the SECOND generated token (the first
        produced by acceptance-rejection) matches plain target sampling."""
        target, tparams, draft, dparams = tiny
        ids = jnp.asarray(np.random.RandomState(72).randint(0, 13, (1, 4)))

        spec_run = target._spec_program(draft, 4, 2, 2, False, 1.0, None,
                                        None)
        plain_run = target._gen_program(4, 2, 1.0, None, None, False)

        n = 5000
        keys = jax.random.split(jax.random.key(5), n)
        spec, _ = jax.vmap(lambda k: spec_run(tparams, dparams, ids, k))(keys)
        plain = jax.vmap(lambda k: plain_run(tparams, ids, k))(keys)
        for pos in (0, 1):
            fs = np.bincount(np.asarray(spec)[:, 0, pos], minlength=13) / n
            fp = np.bincount(np.asarray(plain)[:, 0, pos], minlength=13) / n
            np.testing.assert_allclose(fs, fp, atol=0.035,
                                       err_msg=f"token position {pos}")

    def test_low_temperature_collapses_to_greedy(self, tiny):
        target, tparams, draft, dparams = tiny
        prompt = np.random.RandomState(73).randint(0, 13, (1, 4))
        want = target.generate_speculative(tparams, prompt, 6, draft,
                                           dparams, draft_k=2)
        got = target.generate_speculative(tparams, prompt, 6, draft, dparams,
                                          draft_k=2, greedy=False,
                                          temperature=1e-6,
                                          key=jax.random.key(9))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_deterministic_under_key(self, tiny):
        target, tparams, draft, dparams = tiny
        prompt = np.random.RandomState(74).randint(0, 13, (2, 3))
        k = jax.random.key(11)
        a = target.generate_speculative(tparams, prompt, 5, draft, dparams,
                                        draft_k=3, greedy=False,
                                        temperature=0.9, top_k=8, key=k)
        b = target.generate_speculative(tparams, prompt, 5, draft, dparams,
                                        draft_k=3, greedy=False,
                                        temperature=0.9, top_k=8, key=k)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.shape == (2, 5)


class TestSpeculativeRounds:
    def test_perfect_draft_uses_minimal_rounds(self, model_and_params):
        """Perfect draft ⇒ every round accepts draft_k+1 tokens ⇒ exactly
        ceil((N-1)/(K+1)) rounds.  This is the observable that catches
        draft-cache corruption (e.g. the zero-kv hole after a full-accept
        round): outputs stay lossless regardless, but acceptance — and so
        the round count — degrades."""
        model, params = model_and_params
        prompt = np.random.RandomState(80).randint(0, 97, (1, 5))
        N, K = 9, 3
        toks, rounds = model.generate_speculative(
            params, prompt, N, model, params, draft_k=K, return_rounds=True)
        want = model.generate(params, prompt, N)
        np.testing.assert_array_equal(np.asarray(toks), np.asarray(want))
        assert int(rounds) == -(-(N - 1) // (K + 1)), int(rounds)


class TestCrossFamilySpeculative:
    def test_moe_target_with_gpt_draft(self):
        """The mixin contract makes speculative decoding model-agnostic:
        an ERNIE-MoE target accelerated by a dense GPT draft stays
        bit-lossless vs the MoE's own greedy decode."""
        from paddle_tpu.models.ernie_moe import ErnieMoeConfig, ErnieMoeModel

        paddle.seed(90)
        moe = ErnieMoeModel(ErnieMoeConfig(
            vocab_size=53, hidden_size=32, num_layers=2,
            num_attention_heads=4, num_experts=4, top_k=2,
            max_position_embeddings=32, compute_dtype="float32"))
        mparams = {n: p._data for n, p in moe.named_parameters()}
        paddle.seed(91)
        draft = GPTModel(GPTConfig(
            vocab_size=53, hidden_size=16, num_layers=1,
            num_attention_heads=2, max_position_embeddings=32,
            compute_dtype="float32"))
        dparams = {n: p._data for n, p in draft.named_parameters()}

        prompt = np.random.RandomState(92).randint(0, 53, (1, 4))
        want = moe.generate(mparams, prompt, max_new_tokens=6)
        got = moe.generate_speculative(mparams, prompt, 6, draft, dparams,
                                       draft_k=2)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
