"""KV-cache autoregressive generation (models/gpt.py generate/prefill/
decode_step) vs the no-cache oracle: re-running the full forward on the
growing sequence.  ≙ the reference ecosystem's generation_utils greedy/
sampling contracts + fused_multi_transformer CacheKV semantics."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTConfig, GPTModel


@pytest.fixture(scope="module")
def model_and_params():
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=3,
                    num_attention_heads=4, max_position_embeddings=64,
                    compute_dtype="float32")
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    return model, params


def _oracle_greedy(model, params, prompt, n):
    """No-cache decoding: full forward over the growing sequence."""
    ids = np.asarray(prompt)
    out = []
    for _ in range(n):
        h = model.embed_fn(params, jnp.asarray(ids))
        h = model.scan_blocks(params, h, remat=False)
        logits = model.head_fn(params, h)
        nxt = np.asarray(jnp.argmax(logits[:, -1, :], -1)).astype(np.int64)
        out.append(nxt)
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
    return np.stack(out, axis=1)


class TestGenerate:
    def test_greedy_matches_no_cache_oracle(self, model_and_params):
        model, params = model_and_params
        prompt = np.random.RandomState(0).randint(0, 97, (2, 5))
        want = _oracle_greedy(model, params, prompt, 8)
        got = model.generate(params, prompt, max_new_tokens=8)
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_decode_logits_match_full_forward(self, model_and_params):
        """Cache-path hidden state at position t equals the full-forward
        hidden state at t (the cache IS the attention state, not an
        approximation)."""
        model, params = model_and_params
        ids = np.random.RandomState(1).randint(0, 97, (2, 6))
        max_len = 8

        h_pre, caches = model.prefill(params, jnp.asarray(ids), max_len)
        # feed the true next token (from data, not sampling) through decode
        tok = jnp.asarray(np.random.RandomState(2).randint(0, 97, (2,)))
        dt = jnp.dtype(model.config.compute_dtype)
        h1 = (jnp.take(params["wte"], tok[:, None], axis=0)
              + params["wpe"][6][None, None, :]).astype(dt)
        h1, _ = model.decode_step(params, h1, caches, jnp.asarray(6))

        full = jnp.concatenate([jnp.asarray(ids), tok[:, None]], axis=1)
        hf = model.scan_blocks(params, model.embed_fn(params, full),
                               remat=False)
        np.testing.assert_allclose(np.asarray(h1[:, 0]), np.asarray(hf[:, -1]),
                                   rtol=2e-4, atol=2e-5)
        # prefill hidden states equal full-forward prefix states too
        np.testing.assert_allclose(np.asarray(h_pre), np.asarray(hf[:, :6]),
                                   rtol=2e-4, atol=2e-5)

    def test_single_token_and_cap(self, model_and_params):
        model, params = model_and_params
        prompt = np.zeros((1, 3), np.int64)
        out = model.generate(params, prompt, max_new_tokens=1)
        assert out.shape == (1, 1)
        with pytest.raises(ValueError, match="max_position_embeddings"):
            model.generate(params, prompt, max_new_tokens=62)

    def test_sampling_deterministic_under_key(self, model_and_params):
        model, params = model_and_params
        prompt = np.random.RandomState(3).randint(0, 97, (2, 4))
        k = jax.random.key(42)
        a = model.generate(params, prompt, max_new_tokens=6, greedy=False,
                           temperature=0.8, top_k=10, key=k)
        b = model.generate(params, prompt, max_new_tokens=6, greedy=False,
                           temperature=0.8, top_k=10, key=k)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.shape == (2, 6)
        assert np.all((np.asarray(a) >= 0) & (np.asarray(a) < 97))

    def test_sampling_requires_key(self, model_and_params):
        model, params = model_and_params
        with pytest.raises(ValueError, match="requires key"):
            model.generate(params, np.zeros((1, 2), np.int64), 2, greedy=False)


class TestProgramCache:
    def test_repeat_calls_reuse_compiled_program(self, model_and_params):
        model, params = model_and_params
        prompt = np.zeros((1, 4), np.int64)
        a = model.generate(params, prompt, max_new_tokens=3)
        r1 = model._gen_program(4, 3, 1.0, None, True)
        b = model.generate(params, prompt, max_new_tokens=3)
        r2 = model._gen_program(4, 3, 1.0, None, True)
        assert r1 is r2                       # same memoized jitted program
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_zero_tokens_returns_empty(self, model_and_params):
        model, params = model_and_params
        out = model.generate(params, np.zeros((2, 3), np.int64), 0)
        assert out.shape == (2, 0)
