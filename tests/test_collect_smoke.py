"""Import-time regression gate: every test module must import cleanly.

A broken import does NOT fail the tier-1 run — `pytest
--continue-on-collection-errors` just drops the whole module's tests from
the count (round 5's `from jax import shard_map` regression silently hid
tests/test_spmd_vma_seam.py for a full round).  This test imports every
tests/*.py module IN-PROCESS (modules already imported by the collecting
pytest are free; a standalone run pays one jax import total) and fails
LOUDLY with the offending module and traceback.  tools/collect_smoke.sh is
the standalone subprocess form of the same gate."""

import importlib
import pathlib
import sys
import traceback

HERE = pathlib.Path(__file__).parent


def test_every_test_module_imports():
    failures = []
    for path in sorted(HERE.glob("test_*.py")):
        name = path.stem
        try:
            importlib.import_module(name)
        except Exception:  # noqa: BLE001 — report ALL broken modules
            failures.append(f"{name}:\n{traceback.format_exc()}")
    assert not failures, (
        "test modules with import-time errors (these are silently dropped "
        "from tier-1 counts — fix before anything else):\n\n"
        + "\n".join(failures))


def test_package_namespace_imports():
    """The serving/ops surface this suite leans on must resolve through
    the public namespace (lazy re-exports included)."""
    import paddle_tpu.serving as serving
    for name in ("ContinuousBatchingEngine", "PagedContinuousBatchingEngine",
                 "RaggedPagedContinuousBatchingEngine"):
        assert getattr(serving, name) is not None
    assert "paddle_tpu.serving_paged" in sys.modules
