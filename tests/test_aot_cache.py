"""AOT bucket warmup + persistent executable cache (ISSUE 7).

The tentpole contracts under test:
- disk round trip: compile → serialize → fresh-cache-instance reload →
  identical outputs;
- environment drift (jax version / backend / mesh) INVALIDATES an entry —
  a stale executable recompiles, never runs;
- a warmed engine serves its first request with ZERO compile events (the
  compile-once contract), token-for-token identical to a cold engine;
- a second process reusing the cache dir records ``provenance: disk``
  compile events and writes no new XLA cache files (skipped recompilation);
- purity: lowerings are byte-identical with and without warmup
  instrumentation (extends the PR 4 off-path purity suite).
"""

import json
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit.aot import ExecutableCache, compile_aot, fingerprint
from paddle_tpu.jit.bucketing import bucketize, pow2_bucket, pow2_grid
from paddle_tpu.jit.functional import make_train_step, warm_train_step
from paddle_tpu.models.gpt import GPTConfig, GPTModel
from paddle_tpu.optimizer import Momentum
from paddle_tpu.serving import (ContinuousBatchingEngine,
                                PagedContinuousBatchingEngine,
                                RaggedPagedContinuousBatchingEngine)
from paddle_tpu.telemetry import TrainMonitor, Tracer

# 1 layer keeps every warmup compile cheap; the program FAMILIES (the thing
# under test) are layer-count independent
CFG = dict(vocab_size=64, hidden_size=32, num_layers=1,
           num_attention_heads=2, max_position_embeddings=64,
           compute_dtype="float32")


def _model():
    paddle.seed(0)
    model = GPTModel(GPTConfig(**CFG))
    params = {n: p._data for n, p in model.named_parameters()}
    return model, params


def _ragged(tracer=None, **kw):
    model, params = _model()
    eng = RaggedPagedContinuousBatchingEngine(
        model, params, max_slots=2, max_len=32, block_size=8,
        prompt_buckets=[8, 16], token_budget=12, tracer=tracer, **kw)
    return model, eng


def _serve(eng, prompt=(1, 2, 3, 4), n=3):
    rid = eng.add_request(list(prompt), n)
    return eng.run_to_completion(max_ticks=200)[rid]


@pytest.fixture
def restore_compilation_cache():
    """enable_persistent_compilation_cache mutates process-global jax
    config; put it back so later tests see the default state."""
    prev = jax.config.jax_compilation_cache_dir
    yield
    jax.config.update("jax_compilation_cache_dir", prev)
    from jax._src.compilation_cache import reset_cache
    reset_cache()


# ------------------------------------------------------------- key helper --

class TestKeyHelper:
    def test_fingerprint_stable_and_part_sensitive(self):
        a = fingerprint("prog", (1, 2), "f32")
        assert a == fingerprint("prog", (1, 2), "f32")
        assert a != fingerprint("prog", (1, 3), "f32")
        assert a != fingerprint("prog2", (1, 2), "f32")

    def test_fingerprint_env_sensitive(self):
        # backend is part of the default environment fold-in
        a = fingerprint("prog")
        assert a != fingerprint("prog", backend="tpu-imaginary")
        assert a == fingerprint("prog", backend=jax.default_backend())

    def test_fingerprint_folds_in_sharding_rules_digest(self):
        """Editing the sharding-rule table (ISSUE 16) changes the default
        env fold-in, so layout-sensitive keys miss instead of aliasing."""
        from paddle_tpu.distributed import sharding_rules as sr
        a = fingerprint("prog")
        sr.register_rules(sr.ShardingRules([(r".*", None)],
                                           name="test_fp_rules"))
        try:
            assert fingerprint("prog") != a
            # explicit env exclusion stays rule-blind (compile_aot's key)
            assert (fingerprint("prog", include_env=False)
                    == fingerprint("prog", include_env=False))
        finally:
            sr.unregister_rules("test_fp_rules")
        assert fingerprint("prog") == a

    def test_pow2_grid_is_exactly_the_view_cols_image(self):
        assert pow2_grid(8) == (1, 2, 4, 8)
        assert pow2_grid(1) == (1,)
        # non-power-of-two cap: the clamp value itself is a bucket
        assert pow2_grid(6) == (1, 2, 4, 6)
        assert pow2_bucket(5, 8) == 8
        assert pow2_bucket(5, 6) == 6
        assert pow2_bucket(0, 8) == 1
        for cap in (1, 2, 6, 8, 16):
            for need in range(1, cap + 1):
                assert pow2_bucket(need, cap) in pow2_grid(cap), (need, cap)


# ------------------------------------------------------ persistent cache --

class TestExecutableCache:
    def _compiled(self):
        f = jax.jit(lambda x: x * 3 + 1)
        x = jnp.arange(8.0)
        return f.lower(x).compile(), x

    def test_disk_round_trip_identical_outputs(self, tmp_path):
        compiled, x = self._compiled()
        want = np.asarray(compiled(x))
        cache = ExecutableCache(tmp_path)
        assert cache.put("prog", compiled)
        # fresh instance = fresh-process-style: no in-memory entries
        fresh = ExecutableCache(tmp_path)
        got = fresh.get("prog")
        assert got is not None and fresh.hits_disk == 1
        np.testing.assert_array_equal(np.asarray(got(x)), want)
        # second-level in-process cache: same object, no re-deserialize
        assert fresh.get("prog") is got and fresh.hits_memory == 1

    def test_miss_is_none(self, tmp_path):
        cache = ExecutableCache(tmp_path)
        assert cache.get("never-put") is None and cache.misses == 1

    def _tamper(self, tmp_path, field, value):
        path = os.path.join(str(tmp_path), "manifest.json")
        with open(path) as f:
            manifest = json.load(f)
        for entry in manifest["entries"].values():
            entry[field] = value
        with open(path, "w") as f:
            json.dump(manifest, f)

    def test_jax_version_mismatch_invalidates(self, tmp_path):
        compiled, _ = self._compiled()
        ExecutableCache(tmp_path).put("prog", compiled)
        self._tamper(tmp_path, "jax", "0.0.0")
        fresh = ExecutableCache(tmp_path)
        assert fresh.get("prog") is None and fresh.invalidated == 1

    def test_backend_mismatch_invalidates(self, tmp_path):
        compiled, _ = self._compiled()
        ExecutableCache(tmp_path).put("prog", compiled)
        self._tamper(tmp_path, "backend", "tpu-imaginary")
        fresh = ExecutableCache(tmp_path)
        assert fresh.get("prog") is None and fresh.invalidated == 1

    def test_mesh_mismatch_invalidates(self, tmp_path):
        compiled, _ = self._compiled()
        ExecutableCache(tmp_path).put("prog", compiled, mesh=None)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("x",))
        fresh = ExecutableCache(tmp_path)
        assert fresh.get("prog", mesh=mesh) is None
        assert fresh.invalidated == 1
        # matching mesh=None still loads
        assert fresh.get("prog") is not None

    def test_sharding_rules_mismatch_invalidates(self, tmp_path):
        """A stale-SPEC executable restored from disk must be impossible
        (ISSUE 16): the manifest records the sharding-rules digest, so an
        entry serialized under one rule table refuses to load under
        another — same observable path as jax/backend/mesh drift."""
        from paddle_tpu.distributed import sharding_rules as sr
        compiled, _ = self._compiled()
        ExecutableCache(tmp_path).put("prog", compiled)
        # manifest tamper = an entry written by a process with other rules
        self._tamper(tmp_path, "rules", "0" * 32)
        fresh = ExecutableCache(tmp_path)
        assert fresh.get("prog") is None and fresh.invalidated == 1
        # the live direction too: put under today's rules, register a new
        # rule set, and a fresh-process get must invalidate
        ExecutableCache(tmp_path).put("prog", compiled)
        sr.register_rules(sr.ShardingRules([(r".*", ("data",))],
                                           name="test_aot_rules"))
        try:
            fresh2 = ExecutableCache(tmp_path)
            assert fresh2.get("prog") is None and fresh2.invalidated == 1
        finally:
            sr.unregister_rules("test_aot_rules")
        # rules restored: the entry loads again
        assert ExecutableCache(tmp_path).get("prog") is not None

    def test_corrupt_payload_degrades_to_recompile(self, tmp_path):
        compiled, _ = self._compiled()
        cache = ExecutableCache(tmp_path)
        cache.put("prog", compiled)
        [entry] = cache.entries()
        with open(os.path.join(str(tmp_path), entry["file"]), "wb") as f:
            f.write(b"not a pickle")
        fresh = ExecutableCache(tmp_path)
        assert fresh.get("prog") is None and fresh.invalidated == 1


# ------------------------------------------------------- training-step AOT --

class TestCompileAot:
    def test_cold_then_disk_then_warm(self, tmp_path):
        step = jax.jit(lambda s, x: s + x)
        args = (jnp.ones((4,)), jnp.arange(4.0))
        c1, prov1 = compile_aot(step, args, cache=ExecutableCache(tmp_path),
                                label="t")
        assert prov1 == "cold"
        cache2 = ExecutableCache(tmp_path)
        c2, prov2 = compile_aot(step, args, cache=cache2, label="t")
        assert prov2 == "disk"
        np.testing.assert_array_equal(np.asarray(c1(*args)),
                                      np.asarray(c2(*args)))
        _, prov3 = compile_aot(step, args, cache=cache2, label="t")
        assert prov3 == "warm"

    def test_monitor_records_provenance(self, tmp_path):
        mon = TrainMonitor()
        step = jax.jit(lambda s, x: s - x)
        args = (jnp.ones((4,)), jnp.arange(4.0))
        compile_aot(step, args, cache=ExecutableCache(tmp_path), label="t",
                    monitor=mon)
        compile_aot(step, args, cache=ExecutableCache(tmp_path), label="t",
                    monitor=mon)
        provs = [e["provenance"] for e in mon.events("compile")]
        assert provs == ["cold", "disk"]
        assert mon.summary()["compile"]["cold"] == 1
        assert mon.summary()["compile"]["disk"] == 1

    def test_warm_train_step_matches_live_dispatch(self, tmp_path):
        """The functional.py AOT seam: the warmed executable IS the step's
        own program (lower passes through the telemetry wrappers), so a
        compiled first step equals a live first step bit-for-bit."""
        paddle.seed(0)
        layer = nn.Linear(4, 3)
        step, state = make_train_step(
            layer, nn.MSELoss(), Momentum(learning_rate=0.1, momentum=0.9),
            donate=False)
        rest = (jax.random.key(0), np.float32(0.1), [jnp.ones((8, 4))],
                [jnp.zeros((8, 3))])
        compiled, prov = warm_train_step(step, (state,) + rest,
                                         cache=ExecutableCache(tmp_path))
        assert prov == "cold"
        _, (loss_aot, _) = compiled(state, *rest)
        _, (loss_live, _) = step(state, *rest)
        assert float(loss_aot) == float(loss_live)

    @pytest.mark.slow
    def test_gpt_train_step_exposes_lower(self):
        """make_gpt_train_step's arg-reorder closure passes .lower through
        (the gpt AOT seam) — lowering succeeds and the AOT compile runs."""
        from paddle_tpu.distributed import fleet
        from paddle_tpu.models.gpt import make_gpt_train_step
        from paddle_tpu.optimizer import AdamW
        paddle.seed(0)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        model = GPTModel(GPTConfig(**CFG))
        step, state = make_gpt_train_step(model, AdamW(3e-4), hcg,
                                          remat=False)
        assert hasattr(step, "lower")
        x = jnp.zeros((2, 8), jnp.int32)
        args = (state, jax.random.key(0), np.float32(3e-4), x, x)
        compiled, prov = warm_train_step(step, args, label="gpt")
        assert prov == "cold"
        _, loss = compiled(*args)
        assert np.isfinite(float(loss))


# --------------------------------------------------------- tracer window --

class TestExpectedCompiles:
    def test_warmup_window_disarms_storm_and_resolves_provenance(self,
                                                                 caplog):
        tr = Tracer(recompile_warn_threshold=1)
        tr.tick("E", 0.01)                    # post-warmup from here on
        with caplog.at_level(logging.WARNING, logger="paddle_tpu.telemetry"):
            with tr.expected_compiles(lambda: "disk"):
                tr.compile_event("E", ("k", 1), False, 0.1)
        assert not [r for r in caplog.records
                    if "recompile storm" in r.getMessage()]
        [ev] = tr.events("compile")
        assert ev["expected"] and ev["provenance"] == "disk"
        assert tr.summary()["compile"]["post_warmup_misses"] == 0
        assert tr.summary()["compile"]["disk"] == 1
        # outside the window: default provenance cold, storm arms
        with caplog.at_level(logging.WARNING, logger="paddle_tpu.telemetry"):
            tr.compile_event("E", ("k", 2), False, 0.1)
        assert [r for r in caplog.records
                if "recompile storm" in r.getMessage()]
        assert tr.events("compile")[-1]["provenance"] == "cold"
        assert not tr.events("compile")[-1]["expected"]

    def test_window_scoped_to_grid_keys(self):
        """With warmup_async, live traffic compiles inside the window —
        only the DECLARED grid's misses are excused (code-review catch:
        an unscoped window would mute a real storm for the whole
        warmup)."""
        tr = Tracer(recompile_warn_threshold=1)
        tr.tick("E", 0.01)
        with tr.expected_compiles(lambda: "disk",
                                  keys={"prefill:8", "seg:8:01"}):
            tr.compile_event("E", ("prefill", 8, ("sig",)), False, 0.1)
            # task labels may extend the event label (bools end the
            # label's int run): seg:8 matches task seg:8:01
            tr.compile_event("E", ("seg", 8, True, False, ("sig",)),
                             False, 0.1)
            tr.compile_event("E", ("decode", 4, ("sig",)), False, 0.1)
        evs = tr.events("compile")
        assert [e["expected"] for e in evs] == [True, True, False]
        # the off-grid miss kept default provenance and armed the storm
        assert evs[2]["provenance"] == "cold"
        assert tr.summary()["compile"]["post_warmup_misses"] == 1


# ------------------------------------------------------------ engine warmup --

class TestEngineWarmup:
    def test_warmed_engine_zero_compiles_and_oracle_outputs(self):
        """THE acceptance assertions: after warmup the whole served
        workload fetches only cache hits — zero compile misses, zero
        compile ring events — and outputs are token-for-token identical
        to a cold engine's (scratch dispatch uses a constant key and
        fresh donated caches, never live state).  Also pins purity
        (extends the PR 4 suite): the ragged program's lowering is
        byte-identical between the warmed+traced engine and a bare cold
        one — warmup instrumentation never reaches a compiled program or
        its cache key."""
        _, cold = _ragged()
        want = _serve(cold)
        tr = Tracer()
        _, eng = _ragged(tracer=tr)
        report = eng.warmup(max_workers=1)
        grid = eng.compile_grid()
        assert report["programs"] == len(grid)
        assert [t["label"] for t in report["tasks"]] == grid
        # the ragged grid is exactly one program per table-width bucket
        assert grid == [f"ragged_step:12:{C}" for C in pow2_grid(eng.MB)]
        assert all(e["expected"] for e in tr.events("compile"))
        misses0 = eng._compile_misses
        events0 = len(tr.events("compile"))
        assert _serve(eng) == want
        assert eng._compile_misses == misses0
        assert len(tr.events("compile")) == events0
        # purity: lowering identical with and without warmup
        # instrumentation (same scratch avals on both sides)
        C = 2
        text_inst = eng._build_ragged_step(eng.token_budget, C).lower(
            *eng._ragged_scratch_args(C)).as_text()
        text_bare = cold._build_ragged_step(cold.token_budget, C).lower(
            *cold._ragged_scratch_args(C)).as_text()
        assert text_inst == text_bare

    def test_second_process_reuses_disk(self, tmp_path,
                                        restore_compilation_cache):
        """THE cross-process acceptance: a second engine (fresh model,
        fresh closures — a fresh process in jit-cache terms) warming
        against the same cache dir records provenance: disk for every
        program and writes NO new XLA cache files."""
        tr1 = Tracer()
        _, eng1 = _ragged(tracer=tr1)
        eng1.warmup(cache_dir=tmp_path, max_workers=1)
        assert [e["provenance"] for e in tr1.events("compile")] \
            == ["cold"] * len(eng1.compile_grid())
        xla_dir = os.path.join(str(tmp_path), "xla")
        files_before = set(os.listdir(xla_dir))
        assert any(f.endswith("-cache") for f in files_before)

        tr2 = Tracer()
        _, eng2 = _ragged(tracer=tr2)
        eng2.warmup(cache_dir=tmp_path, max_workers=1)
        evs = tr2.events("compile")
        assert evs and all(e["provenance"] == "disk" for e in evs)
        new = {f for f in os.listdir(xla_dir)
               if f.endswith("-cache")} - files_before
        assert new == set(), f"XLA recompiled: {new}"
        assert int(tr2.registry.value("compile_disk")) == len(evs)
        # and the warmed second engine serves compile-free too
        misses = eng2._compile_misses
        _serve(eng2)
        assert eng2._compile_misses == misses

    @pytest.mark.slow
    def test_paged_engine_grid_covers_serving(self):
        """The paged engine's declared grid (prefill buckets + seg
        variants + decode per table width) really covers a chunked
        workload: zero misses after warmup."""
        model, params = _model()
        eng = PagedContinuousBatchingEngine(
            model, params, max_slots=2, max_len=32, block_size=8,
            prompt_buckets=[8, 16], prefill_chunk=8)
        labels = eng.compile_grid()
        assert "prefill:8" in labels and "decode:1" in labels
        assert any(lbl.startswith("seg:8:") for lbl in labels)
        eng.warmup(max_workers=1)
        misses = eng._compile_misses
        rid = eng.add_request(list(range(1, 13)), 3)   # chunked bucket 16
        out = eng.run_to_completion(max_ticks=200)
        assert eng._compile_misses == misses
        assert len(out[rid]) == 3

    @pytest.mark.slow
    def test_contiguous_engine_warmup_async(self):
        """Base-engine grid + warmup_async: the background Future warms
        the same grid, and the engine then serves compile-free."""
        model, params = _model()
        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=32, prompt_buckets=[8])
        fut = eng.warmup(max_workers=1, block=False)
        report = fut.result(timeout=300)
        assert report["programs"] == len(eng.compile_grid()) == 2
        misses = eng._compile_misses
        rid = eng.add_request([1, 2, 3], 4)
        out = eng.run_to_completion(max_ticks=100)
        assert eng._compile_misses == misses
        assert len(out[rid]) == 4


    @pytest.mark.slow
    def test_speculative_engines_warmup(self):
        """The legacy speculative engines are now shims over the unified
        ragged spec path: their grid is ONE fused draft+verify program
        per table-width bucket (the dual-pool prefill / seg / spec-round
        families are gone), and a warmed shim still serves with zero
        in-serve misses."""
        from paddle_tpu.serving import (PagedSpeculativeBatchingEngine,
                                        SpeculativeBatchingEngine)
        model, params = _model()
        paddle.seed(1)
        draft = GPTModel(GPTConfig(**CFG))
        dparams = {n: p._data for n, p in draft.named_parameters()}
        eng = SpeculativeBatchingEngine(
            model, params, draft, dparams, max_slots=2, max_len=32,
            draft_k=2, prompt_buckets=[8])
        labels = eng.compile_grid()
        assert labels == [f"ragged_spec:{eng.token_budget}:{C}"
                          for C in pow2_grid(eng.MB)]
        eng.warmup(max_workers=1)
        m0 = eng._compile_misses
        rid = eng.add_request([1, 2, 3], 4)
        out = eng.run_to_completion(max_ticks=100)
        assert eng._compile_misses == m0 and len(out[rid]) == 4

        model.__dict__.pop("_serving_programs", None)
        eng2 = PagedSpeculativeBatchingEngine(
            model, params, draft, dparams, max_slots=2, max_len=32,
            draft_k=2, prompt_buckets=[8, 16], block_size=8,
            prefill_chunk=8)       # legacy knob: accepted and dropped
        labels = eng2.compile_grid()
        assert all(lbl.startswith("ragged_spec:") for lbl in labels)
        assert len(labels) == len(pow2_grid(eng2.MB))
        eng2.warmup(max_workers=1)
        m0 = eng2._compile_misses
        eng2.add_request([1, 2, 3], 4)
        eng2.add_request(list(range(1, 13)), 3)      # bucket 16 spans steps
        eng2.run_to_completion(max_ticks=200)
        assert eng2._compile_misses == m0

    def test_ragged_spec_grid_zero_compiles_and_purity(self):
        """The spec-enabled ragged grid: SAME SIZE as the plain ragged
        grid (speculation adds zero program families), zero in-serve
        compiles after warmup, and the fused program's lowering is
        byte-identical between a warmed+traced engine and a bare cold
        one (warmup instrumentation never reaches a compiled program)."""
        model, params = _model()
        paddle.seed(2)
        draft = GPTModel(GPTConfig(**CFG))
        dparams = {n: p._data for n, p in draft.named_parameters()}

        def make(tracer=None):
            return RaggedPagedContinuousBatchingEngine(
                model, params, max_slots=2, max_len=32, block_size=8,
                prompt_buckets=[8, 16], token_budget=12, tracer=tracer,
                draft_model=draft, draft_params=dparams, draft_k=2)

        cold = make()
        want = _serve(cold)
        tr = Tracer()
        eng = make(tracer=tr)
        report = eng.warmup(max_workers=1)
        grid = eng.compile_grid()
        assert report["programs"] == len(grid)
        assert grid == [f"ragged_spec:12:{C}" for C in pow2_grid(eng.MB)]
        _, plain = _ragged()
        assert len(grid) == len(plain.compile_grid())
        misses0 = eng._compile_misses
        events0 = len(tr.events("compile"))
        assert _serve(eng) == want
        assert eng._compile_misses == misses0
        assert len(tr.events("compile")) == events0
        C = 2
        text_inst = eng._build_ragged_spec_step(eng.token_budget, C).lower(
            *eng._ragged_spec_scratch_args(C)).as_text()
        text_bare = cold._build_ragged_spec_step(cold.token_budget, C).lower(
            *cold._ragged_spec_scratch_args(C)).as_text()
        assert text_inst == text_bare


# ------------------------------------------------------------- hapi flops --

class TestDynamicFlopsCache:
    def test_cost_analysis_cached_per_lowered_program(self, monkeypatch):
        """flops() used to re-lower and re-COMPILE the model every call;
        the compile+cost result is now cached on the lowered-program
        digest — a repeat query re-lowers (cheap) but never compiles."""
        from paddle_tpu.hapi import dynamic_flops
        paddle.seed(0)
        net = nn.Linear(4, 3)
        first = dynamic_flops.flops(net, (1, 4))
        calls = [0]
        orig = jax.stages.Lowered.compile

        def counting(self, *a, **kw):
            calls[0] += 1
            return orig(self, *a, **kw)

        monkeypatch.setattr(jax.stages.Lowered, "compile", counting)
        assert dynamic_flops.flops(net, (1, 4)) == first
        assert calls[0] == 0
        # a different input shape is a different program: re-measures
        dynamic_flops.flops(net, (2, 4))
        assert calls[0] == 1

    def test_config_changes_are_not_conflated(self):
        """Same class, same param shapes, different config (stride) must
        not collide: the key is the lowered PROGRAM, not (class,
        shapes)."""
        from paddle_tpu.hapi import dynamic_flops
        paddle.seed(0)
        a = dynamic_flops.flops(nn.Conv2D(3, 8, 3, stride=1, padding=1),
                                (1, 3, 16, 16))
        b = dynamic_flops.flops(nn.Conv2D(3, 8, 3, stride=2, padding=1),
                                (1, 3, 16, 16))
        assert a > 0 and b > 0 and a != b


# --------------------------------------------------------------- bucketize --

class TestBucketizeWarmup:
    def test_warmup_precompiles_every_bucket(self):
        calls = [0]

        def fn(x):
            calls[0] += 1          # trace-time counter: one trace per bucket
            return x * 2

        wrapped = bucketize(fn, buckets=(4, 8), axis=1)
        warmed = wrapped.warmup(jnp.ones((2, 3)))
        assert warmed == [4, 8]
        assert set(wrapped.bucket_calls) == {4, 8}
        assert calls[0] == 2
        # live calls land on warmed buckets: no new traces
        wrapped(jnp.ones((2, 3)))
        wrapped(jnp.ones((2, 7)))
        assert calls[0] == 2


# ------------------------------------------------------------------- CLI --

class TestWarmupCLI:
    @pytest.mark.slow
    def test_main_warms_and_reports(self, tmp_path, capsys,
                                    restore_compilation_cache):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "_warmup_cli", os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "tools", "warmup.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rc = mod.main(["--cache-dir", str(tmp_path), "--engine", "ragged",
                       "--preset", "tiny", "--max-len", "32",
                       "--block-size", "8", "--token-budget", "12",
                       "--buckets", "8"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out.strip())
        assert report["programs"] >= 1
        assert report["compile"]["misses"] >= 1
        assert os.path.isdir(os.path.join(str(tmp_path), "xla"))
