"""Fake-clock traffic simulation harness (paddle_tpu/simulation.py,
ISSUE 11): the sim clock/tracer timebase, the SimEngine scheduling
surface (deterministic streams, cancel, warmup/compile accounting,
death injection), the workload generators, and the TrafficSim driver
against a REAL gateway — all deterministic, no jax, no sleeps."""

import random

import pytest

from paddle_tpu.gateway import ServingGateway
from paddle_tpu.simulation import (SimClock, SimEngine, SimTracer,
                                   TrafficSim, _poisson, diurnal,
                                   flash_crowd, sim_tokens, steady)


class TestClockAndTracer:
    def test_clock_advances_monotonically(self):
        clk = SimClock(5.0)
        assert clk() == 5.0
        clk.advance(2.5)
        assert clk() == 7.5
        with pytest.raises(ValueError):
            clk.advance(-1.0)

    def test_sim_tracer_lives_on_fake_time(self):
        """Ring timestamps and the liveness peek read the SIM clock —
        the gateway's stall/quarantine dial works on simulated time."""
        clk = SimClock()
        tr = SimTracer(clk)
        assert tr.t0 == 0.0 and tr.now() == 0.0
        clk.advance(3.0)
        ev = tr.emit("tick", engine="sim")
        assert ev["ts"] == 3.0
        assert tr.last_event_age_s() == 0.0
        clk.advance(7.0)
        assert tr.last_event_age_s() == 7.0


class TestSimEngine:
    def test_deterministic_streams_and_finish(self):
        eng = SimEngine(max_slots=2)
        sig = []
        r0 = eng.add_request([1, 2, 3], 4,
                             on_token=lambda r, t, d: sig.append((r, t, d)))
        r1 = eng.add_request([9], 2)
        while eng.pending():
            eng.step()
        got = eng.pop_finished()
        assert got[r0] == sim_tokens([1, 2, 3], 4)
        assert got[r1] == sim_tokens([9], 2)
        # the stream delivered token-for-token with done on the last
        assert [t for r, t, d in sig if r == r0] == got[r0]
        assert sig[-1][2] is True or any(d for r, t, d in sig if r == r0)

    def test_slots_bound_concurrency(self):
        eng = SimEngine(max_slots=1)
        eng.add_request([1], 3)
        eng.add_request([2], 3)
        eng.step()
        assert len(eng._active) == 1 and len(eng._queue) == 1

    def test_cancel_queued_and_active(self):
        eng = SimEngine(max_slots=1)
        sig = []
        r0 = eng.add_request([1], 5)
        r1 = eng.add_request([2], 5,
                             on_token=lambda r, t, d: sig.append((t, d)))
        eng.step()                       # r0 active, r1 queued
        assert eng.cancel(r1)            # queued-side
        assert sig[-1] == (None, True)   # terminal signal
        assert eng.cancel(r0)            # active-side frees the slot
        assert not eng.cancel(r0)        # already gone
        assert not eng.pending()
        assert eng.metrics()["requests_cancelled"] == 2

    def test_warmup_zero_in_serve_compiles(self):
        """A warmed engine pays NO in-serve compile; an unwarmed one pays
        one per program family it dispatches — the PR 6 contract the
        acceptance scenario pins on spawned replicas."""
        warm = SimEngine(max_slots=2, prompt_buckets=(8, 16))
        rep = warm.warmup(cache_dir="/tmp/unused")
        assert rep["programs"] == 3      # prefill:8, prefill:16, decode
        warm.add_request([1, 2], 2)
        while warm.pending():
            warm.step()
        assert warm.in_serve_compiles == 0

        cold = SimEngine(max_slots=2, prompt_buckets=(8, 16))
        cold.add_request([1, 2], 2)
        while cold.pending():
            cold.step()
        assert cold.in_serve_compiles == 2      # prefill:8 + decode

    def test_warmup_unsupported_raises(self):
        eng = SimEngine(warmup_unsupported=True)
        with pytest.raises(NotImplementedError):
            eng.warmup()

    def test_warmup_compiles_are_expected_on_tracer(self):
        """Warmup misses sit in an expected_compiles window (tagged, storm
        warning ignores them) — the same discipline as jit/aot.py."""
        clk = SimClock()
        tr = SimTracer(clk, recompile_warn_threshold=1)
        eng = SimEngine(tracer=tr)
        eng.warmup()
        misses = [e for e in tr.events("compile") if not e["hit"]]
        assert len(misses) == 3 and all(e["expected"] for e in misses)
        assert not tr._warned_storm

    def test_kill_freezes_engine_and_tracer(self):
        clk = SimClock()
        tr = SimTracer(clk)
        eng = SimEngine(tracer=tr)
        eng.add_request([1], 8)
        eng.step()
        assert tr.last_event_age_s() == 0.0
        eng.kill()
        before = eng._active[0].emitted
        for _ in range(5):
            clk.advance(1.0)
            eng.step()
        assert eng._active[0].emitted == before     # no progress
        assert tr.last_event_age_s() == 5.0         # stall age grows


class TestWorkloads:
    def test_steady_and_flash_crowd_shapes(self):
        r = steady(2.0)
        assert r(0) == r(1e6) == 2.0
        f = flash_crowd(1.0, 10.0, 100.0, 50.0)
        assert f(99.9) == 1.0 and f(100.0) == 10.0
        assert f(149.9) == 10.0 and f(150.0) == 1.0

    def test_diurnal_trough_peak(self):
        d = diurnal(1.0, 9.0, period_s=100.0)
        assert d(0.0) == pytest.approx(1.0)          # trough at phase
        assert d(50.0) == pytest.approx(9.0)         # peak mid-period
        assert d(100.0) == pytest.approx(1.0)        # back to trough
        assert all(1.0 - 1e-9 <= d(t) <= 9.0 + 1e-9
                   for t in range(0, 200, 7))

    def test_poisson_seeded_and_sane(self):
        rng = random.Random(7)
        a = [_poisson(rng, 2.0) for _ in range(200)]
        b = [_poisson(random.Random(7), 2.0) for _ in range(1)]
        assert a[0] == b[0]                          # seeded → replayable
        mean = sum(a) / len(a)
        assert 1.5 < mean < 2.5                      # λ=2 within tolerance
        assert _poisson(rng, 0.0) == 0


class TestTrafficSim:
    def _gateway(self, clk, replicas=2, **kw):
        gw = ServingGateway(clock=clk, tracer=SimTracer(clk), **kw)
        for i in range(replicas):
            eng = SimEngine(max_slots=4, tracer=SimTracer(clk))
            eng.warmup()
            gw.add_replica(eng, f"r{i}")
        return gw

    def test_steady_run_finishes_everything(self):
        clk = SimClock()
        gw = self._gateway(clk)
        sim = TrafficSim(gw, clk, steady(2.0), dt=0.25, seed=3)
        rep = sim.run(60.0)
        assert rep["offered"] > 60                   # λ·T ≈ 120
        assert rep["outcomes"] == {"finished": rep["offered"]}
        assert rep["dropped"] == []
        assert rep["shed_rate"] == 0.0
        assert rep["ttft_s"]["p99"] is not None
        assert rep["end_t"] >= 60.0
        # stream integrity: every finished handle carries its oracle
        for h in sim.handles:
            assert h.tokens == sim_tokens(h.prompt, h.max_new_tokens)

    def test_same_seed_replays_identical_scenario(self):
        def once():
            clk = SimClock()
            gw = self._gateway(clk)
            sim = TrafficSim(gw, clk, flash_crowd(1.0, 5.0, 10.0, 10.0),
                             dt=0.25, seed=11)
            rep = sim.run(40.0)
            return (rep["offered"], rep["outcomes"], rep["ttft_s"])
        assert once() == once()

    def test_overload_sheds_structured_never_drops(self):
        clk = SimClock()
        gw = self._gateway(clk, replicas=1, max_queue_depth=8)
        sim = TrafficSim(gw, clk, steady(20.0), dt=0.25, seed=5)
        rep = sim.run(20.0)
        assert rep["outcomes"].get("shed", 0) > 0
        assert rep["shed_rate"] > 0.0
        assert rep["dropped"] == []                  # shed ≠ dropped
        assert rep["offered"] == sum(rep["outcomes"].values())

    def test_injection_fires_at_time(self):
        clk = SimClock()
        gw = self._gateway(clk)
        fired_at = []
        sim = TrafficSim(gw, clk, steady(1.0), dt=0.5, seed=1)
        sim.at(5.0, lambda: fired_at.append(clk()), "probe")
        rep = sim.run(10.0)
        assert rep["injections_fired"] == ["probe"]
        assert fired_at and 5.0 <= fired_at[0] < 5.5 + 1e-9

    def test_timeline_sampled(self):
        clk = SimClock()
        gw = self._gateway(clk)
        sim = TrafficSim(gw, clk, steady(1.0), dt=0.5, seed=2,
                         sample_every_s=2.0)
        rep = sim.run(10.0, drain=False)
        ts = [s["t"] for s in rep["timeline"]]
        assert ts == sorted(ts) and len(ts) >= 5
        assert all(s["active"] == 2 for s in rep["timeline"])
        assert all("rate" in s and "queued" in s for s in rep["timeline"])

    def test_replica_death_requests_still_finish(self):
        """Death injection end-to-end WITHOUT an autoscaler: the killed
        replica stalls, the gateway quarantines it on the fake clock,
        and every request still finishes on the survivor with the oracle
        stream — zero drops."""
        clk = SimClock()
        gw = self._gateway(clk, replicas=2, stall_threshold_s=3.0)
        sim = TrafficSim(gw, clk, steady(2.0), dt=0.25, seed=9)
        sim.at(5.0, gw.replica("r0").engine.kill, "kill r0")
        rep = sim.run(30.0)
        assert rep["injections_fired"] == ["kill r0"]
        assert gw.replica("r0").state == "quarantined"
        assert rep["dropped"] == []
        assert rep["outcomes"] == {"finished": rep["offered"]}
        for h in sim.handles:
            assert h.tokens == sim_tokens(h.prompt, h.max_new_tokens)
        assert gw.metrics().get("rerouted", 0) >= 0


class TestSpecAcceptanceModel:
    """SimEngine's seeded speculative-acceptance model (ISSUE 13): the
    pacing scales with acceptance exactly like the real ragged spec
    engine, the token STREAM stays the sim_tokens oracle, and everything
    replays deterministically from (spec_seed, rid, emitted)."""

    def _drain(self, eng):
        ticks = 0
        while eng.pending():
            eng.step()
            ticks += 1
            assert ticks < 1000
        return ticks

    def test_deterministic_and_stream_exact(self):
        def run():
            eng = SimEngine(max_slots=2, draft_k=3, acceptance=0.8,
                            spec_seed=5)
            streams = {}
            rids = [eng.add_request([3, 1, 4], 12,
                                    on_token=lambda r, t, d:
                                    streams.setdefault(r, []).append(t)),
                    eng.add_request([2, 7], 9)]
            return eng, streams, rids, self._drain(eng)

        e1, s1, rids1, t1 = run()
        _e2, s2, _rids2, t2 = run()
        assert t1 == t2 and s1 == s2             # same seeds, same replay
        assert s1[rids1[0]] == sim_tokens([3, 1, 4], 12)
        m = e1.metrics()
        assert m["spec_rounds"] > 0 and m["tokens_drafted"] > 0
        assert 0.0 < m["acceptance_rate"] <= 1.0
        # acceptance > 0 shortens the trajectory vs plain 1-token ticks
        plain = SimEngine(max_slots=2)
        plain.add_request([3, 1, 4], 12)
        plain.add_request([2, 7], 9)
        assert t1 < self._drain(plain)

    def test_per_request_acceptance_range(self):
        eng = SimEngine(max_slots=1, draft_k=4, acceptance=(0.1, 0.9),
                        spec_seed=2)
        ps = {eng._req_acceptance(rid) for rid in range(16)}
        assert len(ps) > 1                       # genuinely per-request
        assert all(0.1 <= p <= 0.9 for p in ps)
        assert eng._req_acceptance(3) == eng._req_acceptance(3)

    def test_mixed_spec_fleet_through_gateway(self):
        """A spec replica and a plain replica behind a real gateway on
        the fake clock: zero drops, spec counters tick, and the whole
        scenario replays identically — the deterministic mixed-spec
        traffic the autoscaler/chaos suites can now draw on."""
        def run():
            clock = SimClock()
            gw = ServingGateway(clock=clock, tracer=SimTracer(clock))
            gw.add_replica(SimEngine(max_slots=4,
                                     tracer=SimTracer(clock),
                                     draft_k=4, acceptance=(0.3, 0.9),
                                     spec_seed=1), "spec")
            gw.add_replica(SimEngine(max_slots=4,
                                     tracer=SimTracer(clock)), "plain")
            sim = TrafficSim(gw, clock, steady(2.0), dt=0.25, seed=3)
            rep = sim.run(30.0)
            return gw, rep

        gw1, rep1 = run()
        _gw2, rep2 = run()
        assert rep1["dropped"] == []
        m = gw1.replica("spec").engine.metrics()
        assert m["tokens_drafted"] > 0 and m["acceptance_rate"] > 0.0
        assert gw1.replica("plain").engine.metrics().get(
            "tokens_drafted", 0) == 0
        assert rep1["outcomes"] == rep2["outcomes"]
        assert rep1["ttft_s"] == rep2["ttft_s"]
