"""incubate ops / fused layers / utils parity tests."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate as incubate


class TestSegmentOps:
    def test_segment_reductions_match_numpy(self):
        rng = np.random.RandomState(0)
        x = rng.standard_normal((8, 3)).astype(np.float32)
        ids = np.array([0, 0, 1, 1, 1, 2, 3, 3])
        xt, it = paddle.to_tensor(x), paddle.to_tensor(ids)
        for name, red in [("segment_sum", np.sum), ("segment_mean", np.mean),
                          ("segment_max", np.max), ("segment_min", np.min)]:
            out = np.asarray(getattr(incubate, name)(xt, it)._data)
            for s in range(4):
                np.testing.assert_allclose(out[s], red(x[ids == s], axis=0),
                                           rtol=1e-5, err_msg=name)

    def test_graph_send_recv(self):
        x = paddle.to_tensor(np.array([[1.0], [2.0], [4.0]], np.float32))
        src = paddle.to_tensor(np.array([0, 1, 2, 0]))
        dst = paddle.to_tensor(np.array([1, 2, 1, 0]))
        out = np.asarray(incubate.graph_send_recv(x, src, dst, "sum")._data)
        np.testing.assert_allclose(out.ravel(), [1.0, 5.0, 2.0])
        out = np.asarray(incubate.graph_send_recv(x, src, dst, "mean")._data)
        np.testing.assert_allclose(out.ravel(), [1.0, 2.5, 2.0])
        out = np.asarray(incubate.graph_send_recv(x, src, dst, "max")._data)
        np.testing.assert_allclose(out.ravel(), [1.0, 4.0, 2.0])

    def test_softmax_mask_fuse(self):
        rng = np.random.RandomState(1)
        x = rng.standard_normal((2, 4, 4)).astype(np.float32)
        mask = np.where(rng.rand(2, 4, 4) > 0.5, 0.0, -1e30).astype(np.float32)
        out = np.asarray(incubate.softmax_mask_fuse(
            paddle.to_tensor(x), paddle.to_tensor(mask))._data)
        z = x + mask
        ref = np.exp(z - z.max(-1, keepdims=True))
        ref = ref / ref.sum(-1, keepdims=True)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-6)

        tri = np.asarray(incubate.softmax_mask_fuse_upper_triangle(
            paddle.to_tensor(x))._data)
        assert np.allclose(np.triu(tri[0], 1), 0.0, atol=1e-7)
        np.testing.assert_allclose(tri.sum(-1), 1.0, rtol=1e-5)


class TestFusedLayers:
    def test_fused_encoder_layer_runs_and_trains(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        layer = incubate.nn.FusedTransformerEncoderLayer(
            d_model=16, nhead=4, dim_feedforward=32, dropout_rate=0.0)
        x = paddle.to_tensor(np.random.RandomState(0)
                             .standard_normal((2, 8, 16)).astype(np.float32))
        out = layer(x)
        assert tuple(out.shape) == (2, 8, 16)
        opt = paddle.optimizer.Adam(1e-3, parameters=layer.parameters())
        loss = (out ** 2).mean()
        loss.backward()
        opt.step()
        grads = [p for p in layer.parameters() if p._grad is not None]
        assert len(grads) > 0

    def test_fused_mha_parity_with_dense(self):
        """dropout=0, no mask: block = LN-free residual attention; check
        against a manual composition of the same submodules."""
        import paddle_tpu.nn as nn
        paddle.seed(1)
        mha = incubate.nn.FusedMultiHeadAttention(
            embed_dim=8, num_heads=2, dropout_rate=0.0, attn_dropout_rate=0.0)
        mha.eval()
        x = paddle.to_tensor(np.random.RandomState(2)
                             .standard_normal((1, 4, 8)).astype(np.float32))
        out = mha(x)
        from paddle_tpu.ops.attention import scaled_dot_product_attention
        qkv = mha.qkv(x)
        q, k, v = [t.reshape([1, 4, 2, 4]) for t in qkv.chunk(3, axis=-1)]
        att = scaled_dot_product_attention(q, k, v, training=False)
        ref = mha.ln(x + mha.out_proj(att.reshape([1, 4, 8])))
        np.testing.assert_allclose(np.asarray(out._data),
                                   np.asarray(ref._data), rtol=1e-5, atol=1e-6)


class TestUtils:
    def test_deprecated_and_require_version(self):
        from paddle_tpu.utils import deprecated, require_version

        @deprecated(update_to="paddle.new_thing", since="0.1")
        def old():
            return 42

        with pytest.warns(DeprecationWarning, match="new_thing"):
            assert old() == 42
        require_version("0.0.1")
        with pytest.raises(Exception, match="required min"):
            require_version("99.0")

    def test_unique_name(self):
        from paddle_tpu.utils import unique_name
        a = unique_name.generate("fc")
        b = unique_name.generate("fc")
        assert a != b and a.startswith("fc_")
        with unique_name.guard():
            c = unique_name.generate("fc")
            assert c == "fc_0"
        d = unique_name.generate("fc")
        assert d == "fc_2"  # outer generator resumed

    def test_dlpack_roundtrip(self):
        from paddle_tpu.utils import dlpack
        t = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        obj = dlpack.to_dlpack(t)
        back = dlpack.from_dlpack(obj)
        np.testing.assert_array_equal(np.asarray(back._data),
                                      np.asarray(t._data))
        # cross-framework: torch consumes our export, we consume torch's
        import torch
        tt = torch.from_dlpack(dlpack.to_dlpack(t))
        np.testing.assert_array_equal(tt.numpy(), np.asarray(t._data))
        ours = dlpack.from_dlpack(torch.arange(4, dtype=torch.float32))
        np.testing.assert_array_equal(np.asarray(ours._data),
                                      [0.0, 1.0, 2.0, 3.0])
        with pytest.raises(TypeError, match="__dlpack__"):
            dlpack.from_dlpack("nope")

    def test_run_check_and_download_gate(self, capsys):
        from paddle_tpu.utils import run_check, download
        run_check()
        assert "installed successfully" in capsys.readouterr().out
        with pytest.raises(RuntimeError, match="no network egress"):
            download.get_weights_path_from_url("https://example.com/w.pd")

    def test_incubate_layer_helper_and_pass(self):
        with pytest.raises(RuntimeError, match="nn.Layer"):
            incubate.LayerHelper()
        incubate.fuse_resnet_unit_pass()  # documented no-op


class TestIncubateReviewRegressions:
    def test_segment_max_empty_segment_zeroed(self):
        x = paddle.to_tensor(np.array([[1.0], [2.0], [3.0]], np.float32))
        ids = paddle.to_tensor(np.array([0, 0, 2]))
        out = np.asarray(incubate.segment_max(x, ids)._data).ravel()
        np.testing.assert_allclose(out, [2.0, 0.0, 3.0])  # seg 1 zero, not -inf
        out = np.asarray(incubate.segment_min(x, ids)._data).ravel()
        np.testing.assert_allclose(out, [1.0, 0.0, 3.0])

    def test_fused_mha_rejects_cross_attention(self):
        mha = incubate.nn.FusedMultiHeadAttention(8, 2)
        q = paddle.to_tensor(np.zeros((1, 4, 8), np.float32))
        k = paddle.to_tensor(np.zeros((1, 4, 8), np.float32))
        with pytest.raises(NotImplementedError, match="self-attention"):
            mha(q, key=k)
        with pytest.raises(NotImplementedError, match="kdim"):
            incubate.nn.FusedMultiHeadAttention(8, 2, kdim=16)
        with pytest.raises(ValueError, match="num_heads \\(3\\) must divide"):
            incubate.nn.FusedMultiHeadAttention(8, 3)
        # single LayerNorm: no dead params in state_dict
        names = [n for n, _ in mha.named_parameters()]
        assert not any("pre_ln" in n for n in names)

    def test_require_version_max_boundary(self):
        from paddle_tpu.utils import require_version
        require_version("0.0.1", max_version="0.1")  # 0.1.0 satisfies max 0.1

    def test_unique_name_string_prefix(self):
        from paddle_tpu.utils import unique_name
        with unique_name.guard("pre_"):
            assert unique_name.generate("fc") == "pre_fc_0"
