"""Training telemetry (ISSUE 4): TrainMonitor over the ring-buffer Tracer,
monitor= threading through the step builders and hapi, the numerics
watchdog, HBM census, cross-host aggregation, and the satellites (fused
GradScaler sync, all_reduce_metrics, Profiler.step items/sec).

The tentpole contract under test: with telemetry DISABLED an instrumented
train step produces the SAME lowering/cache key and adds at most one
attribute check (the hapi hot path) — and with it enabled, every step
becomes a structured event that round-trips through the frozen PR 2
exports (JSONL, chrome trace, Prometheus)."""

import json
import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import amp, telemetry
from paddle_tpu.jit.functional import make_train_step
from paddle_tpu.optimizer import Adam, Momentum
from paddle_tpu.telemetry import (TrainMonitor, chrome_trace_from_jsonl,
                                  current_monitor, instrument_train_step,
                                  set_active_monitor)


def _tiny_step(monitor=None, donate=False, seed=0):
    paddle.seed(seed)
    layer = nn.Linear(4, 3)
    step, state = make_train_step(layer, nn.MSELoss(),
                                  Momentum(learning_rate=0.1, momentum=0.9),
                                  donate=donate, monitor=monitor)
    x = jnp.ones((8, 4))
    y = jnp.zeros((8, 3))
    return step, state, (jax.random.key(0), np.float32(0.1), [x], [y])


class TestOffPathPurity:
    def test_monitor_none_returns_bare_step(self):
        """monitor=None adds NOTHING: instrument_train_step is an identity
        (no wrapper frame, no per-step checks)."""
        step, _, _ = _tiny_step()
        assert instrument_train_step(step, None, "x") is step

    def test_identical_lowering_with_and_without_monitor(self):
        """THE acceptance assertion: the compiled program (and hence its
        cache key) is byte-identical with telemetry on or off — the
        monitor wraps OUTSIDE the jit boundary."""
        step_off, st, rest = _tiny_step(seed=1)
        step_on, _, _ = _tiny_step(monitor=TrainMonitor(), seed=1)
        off = step_off.lower(st, *rest).as_text()
        on = step_on.lower(st, *rest).as_text()
        assert off == on

    def test_fit_without_callback_never_touches_monitor(self, monkeypatch):
        """Default Model.fit (no TelemetryCallback): every TrainMonitor
        entry point is boobytrapped and a fit completes anyway — the hot
        path is one attribute check against None."""
        def boom(*a, **kw):
            raise AssertionError("TrainMonitor touched with telemetry off")

        for meth in ("record_step", "record_sync", "record_compile",
                     "observe_loss", "observe_scaler", "hbm_census",
                     "aggregate"):
            monkeypatch.setattr(TrainMonitor, meth, boom)
        from paddle_tpu.hapi import Model
        paddle.seed(2)
        m = Model(nn.Linear(4, 2), inputs=[None])
        m.prepare(Adam(0.01, parameters=m.parameters()), nn.MSELoss())
        assert m._monitor is None
        xs = np.ones((8, 4), "float32")
        ys = np.zeros((8, 2), "float32")
        m.fit([(xs, ys)], epochs=1, verbose=0)

    def test_one_sync_only_on_first_call(self):
        """The instrumented step blocks exactly once: the first call is the
        compile event ONLY (it pays trace+XLA inside dispatch and must not
        pollute step percentiles); steady-state steps stay async."""
        mon = TrainMonitor()
        step, st, rest = _tiny_step(monitor=mon)
        for i in range(4):
            st, _ = step(st, *rest)
        comp = mon.events("compile")
        assert len(comp) == 1 and comp[0]["wall_s"] > 0
        assert mon.summary()["compile"]["misses"] == 1
        steps = mon.events("train_step")
        assert len(steps) == 3                 # 4 calls - 1 compile call
        assert all(e["trainer"] == "train_step" for e in steps)
        # steady-state dispatch is orders faster than the compile call —
        # the percentiles must not have absorbed it
        assert max(e["dur_s"] for e in steps) < comp[0]["wall_s"]
        # batch heuristic: x (8, 4) is the largest leaf — lead dim examples
        assert steps[0]["examples"] == 8


class TestWatchdog:
    def test_non_finite_fires_and_warns_once(self, caplog):
        mon = TrainMonitor()
        mon.observe_loss(1.0)
        with caplog.at_level(logging.WARNING, logger="paddle_tpu.telemetry"):
            assert mon.observe_loss(float("nan")) == "non_finite"
            assert mon.observe_loss(float("inf")) == "non_finite"
        warns = [r for r in caplog.records
                 if "numerics watchdog" in r.getMessage()]
        assert len(warns) == 1                     # storm-dial: warn ONCE
        evs = mon.events("watchdog")
        assert [e["what"] for e in evs] == ["non_finite", "non_finite"]
        assert mon.summary()["watchdog"]["non_finite"] == 2

    def test_loss_spike_vs_ema(self):
        mon = TrainMonitor(spike_factor=10.0, spike_min_steps=5)
        for _ in range(6):
            assert mon.observe_loss(1.0) is None
        assert mon.observe_loss(50.0) == "loss_spike"
        ev = mon.events("watchdog")[-1]
        assert ev["loss"] == 50.0 and abs(ev["ema"] - 1.0) < 1e-9
        # the spike did NOT fold into the EMA: a second spike re-fires
        assert mon.observe_loss(50.0) == "loss_spike"
        assert mon.summary()["watchdog"]["loss_spikes"] == 2
        # below min_steps no spike can fire
        fresh = TrainMonitor(spike_min_steps=5)
        fresh.observe_loss(1.0)
        assert fresh.observe_loss(1000.0) is None

    def test_watchdog_rides_fit_loss_fetch(self):
        """An injected NaN batch surfaces as a watchdog event through the
        normal fit log-freq loss fetch — no extra syncs were added to see
        it."""
        from paddle_tpu.hapi import Model
        from paddle_tpu.callbacks import TelemetryCallback
        paddle.seed(4)
        m = Model(nn.Linear(4, 2), inputs=[None])
        m.prepare(Adam(0.01, parameters=m.parameters()), nn.MSELoss())
        xs = np.ones((8, 4), "float32")
        bad = np.full((8, 4), np.nan, "float32")
        ys = np.zeros((8, 2), "float32")
        mon = TrainMonitor()
        m.fit([(xs, ys), (bad, ys)], epochs=1, log_freq=1, verbose=0,
              callbacks=[TelemetryCallback(monitor=mon)])
        assert any(e["what"] == "non_finite" for e in mon.events("watchdog"))


class TestHBMCensus:
    def test_byte_accounting_split(self):
        mon = TrainMonitor()
        params = {"w": jnp.ones((8, 4), jnp.float32)}          # 128 B
        opt = {"m": jnp.zeros((8, 4), jnp.float32),            # 128 B
               "v": jnp.zeros((4,), jnp.float32)}              # 16 B
        census = mon.hbm_census(params=params, opt=opt)
        assert census["params_bytes"] == 128
        assert census["opt_bytes"] == 144
        assert census["total_bytes"] >= 272
        assert census["peak_bytes"] == census["total_bytes"]
        # gauges + set_max peak land on the registry (Prometheus-visible)
        assert mon.registry.value("hbm_params_bytes") == 128
        assert mon.registry.value("hbm_peak_bytes") >= 272
        text = mon.prometheus_text()
        assert "# TYPE paddle_tpu_train_hbm_peak_bytes gauge" in text
        # peak is a high-water mark: a smaller second census keeps it
        del params["w"]
        c2 = mon.hbm_census(params=params, opt=opt)
        assert c2["peak_bytes"] >= c2["total_bytes"]
        assert mon.events("hbm")


class TestAggregation:
    def test_single_process_identity_and_skew(self):
        mon = TrainMonitor()
        for _ in range(3):
            mon.record_step(0.01, examples=8, tokens=64)
        agg = mon.aggregate()
        assert agg["world"] == 1
        assert agg["steps"] == 3.0
        assert agg["tokens"] == 192.0
        assert agg["straggler_skew"] == pytest.approx(1.0)
        assert agg["global_tokens_per_sec"] > 0
        assert mon.events("aggregate")

    def test_all_reduce_metrics_one_collective(self, monkeypatch):
        from paddle_tpu.distributed.fleet.metrics import metric
        calls = []
        orig = metric._allreduce

        def counting(value, op="sum"):
            calls.append(op)
            return orig(value, op)

        monkeypatch.setattr(metric, "_allreduce", counting)
        d = {"a": 1.0, "b": 2.5, "c": -3.0}
        out = metric.all_reduce_metrics(d, "sum")
        assert out == d                            # identity in one process
        assert calls == ["sum"]                    # ONE collective
        assert metric.all_reduce_metrics({}) == {}
        assert len(calls) == 1                     # empty dict: no collective

    def test_fleet_metric_functions_still_work(self):
        from paddle_tpu.distributed import fleet
        assert fleet.metrics.sum(np.array([1.0, 2.0])) == 3.0
        assert fleet.metrics.max(np.array([1.0, 5.0])) == 5.0
        assert fleet.metrics.all_reduce_metrics({"x": 2.0})["x"] == 2.0


class TestAmpScaler:
    def _fake_opt(self, grads):
        from paddle_tpu.core.tensor import Parameter
        ps = []
        for g in grads:
            p = Parameter(jnp.zeros_like(g))
            p._grad = g
            ps.append(p)

        class FakeOpt:
            _parameter_list = ps

            def step(self):
                pass

        return FakeOpt(), ps

    def test_unscale_single_sync_and_correctness(self, monkeypatch):
        """The fused finiteness reduction pays ONE host sync for the whole
        parameter list (was one bool() per parameter)."""
        calls = []
        real = bool
        monkeypatch.setattr(amp, "_host_bool",
                            lambda x: calls.append(1) or real(x))
        opt, ps = self._fake_opt([jnp.ones((3,)) * 2.0 for _ in range(5)])
        sc = amp.GradScaler(init_loss_scaling=4.0)
        sc.unscale_(opt)
        assert len(calls) == 1
        assert not sc._found_inf
        np.testing.assert_allclose(np.asarray(ps[0]._grad), 0.5)
        # idempotent: second unscale_ is a no-op until update()
        sc.unscale_(opt)
        assert len(calls) == 1

    def test_found_inf_and_telemetry_events(self):
        mon = TrainMonitor()
        prev = set_active_monitor(mon)
        try:
            grads = [jnp.ones((3,)),
                     jnp.asarray([1.0, np.inf, 2.0]), jnp.ones((2,))]
            opt, _ = self._fake_opt(grads)
            sc = amp.GradScaler(init_loss_scaling=8.0,
                                decr_every_n_nan_or_inf=1)
            sc.unscale_(opt)
            assert sc._found_inf
            sc.update()
            assert sc.get_loss_scaling() == 4.0
            whats = [e["what"] for e in mon.events("amp")]
            assert whats == ["found_inf", "scale_change"]
            s = mon.summary()["amp"]
            assert s["found_inf"] == 1 and s["scale_changes"] == 1
            assert s["scale"] == 4.0
        finally:
            set_active_monitor(prev)

    def test_no_monitor_no_cost(self):
        assert current_monitor() is None
        opt, _ = self._fake_opt([jnp.ones((2,))])
        sc = amp.GradScaler(init_loss_scaling=2.0)
        sc.unscale_(opt)                           # must not raise
        sc.update()


class TestTelemetryCallback:
    def _fit(self, mon, batches=4, epochs=2, **cb_kw):
        from paddle_tpu.hapi import Model
        from paddle_tpu.callbacks import TelemetryCallback
        paddle.seed(5)
        m = Model(nn.Linear(4, 2), inputs=[None])
        m.prepare(Adam(0.01, parameters=m.parameters()), nn.MSELoss())
        rng = np.random.RandomState(0)
        data = [(rng.randn(8, 4).astype("float32"),
                 rng.randn(8, 2).astype("float32")) for _ in range(batches)]
        cb = TelemetryCallback(monitor=mon, **cb_kw)
        m.fit(data, epochs=epochs, log_freq=2, verbose=0, callbacks=[cb])
        return m, cb

    def test_fit_records_steps_syncs_and_census(self, tmp_path):
        mon = TrainMonitor()
        jsonl = tmp_path / "train.jsonl"
        m, cb = self._fit(mon, jsonl_path=str(jsonl))
        steps = mon.events("train_step")
        # 4 batches x 2 epochs, minus the first call = the compile event
        assert len(steps) == 7
        assert all(e["trainer"] == "hapi" and e["examples"] == 8
                   for e in steps)
        comp = mon.events("compile")
        assert len(comp) == 1 and comp[0]["key"] == "hapi_step"
        assert mon.events("sync")                  # log-freq loss fetches
        assert mon.events("hbm")                   # train-end census
        s = mon.summary()
        assert s["steps"] == 7
        assert s["examples_per_sec"] > 0
        assert s["watchdog"]["last_loss"] is not None
        # active monitor restored AND detached from the model after fit —
        # a later fit without the callback is back to one attr check
        assert current_monitor() is None
        assert m._monitor is None
        # JSONL dumped at train end and converts to a chrome trace
        ct = chrome_trace_from_jsonl(str(jsonl))
        names = {e["name"] for e in ct["traceEvents"]}
        assert "train_step" in names and "sync" in names
        json.dumps(ct)

    def test_default_monitor_and_reuse(self):
        from paddle_tpu.callbacks import TelemetryCallback
        cb = TelemetryCallback()
        assert isinstance(cb.monitor, TrainMonitor)

    def test_fit_exception_still_tears_down(self):
        """A raise mid-fit skips on_train_end; fit's finally must still
        restore the active monitor and detach the model."""
        from paddle_tpu.hapi import Model
        from paddle_tpu.callbacks import TelemetryCallback
        paddle.seed(8)
        m = Model(nn.Linear(4, 2), inputs=[None])
        m.prepare(Adam(0.01, parameters=m.parameters()), nn.MSELoss())
        mon = TrainMonitor()

        def bad_batches():
            yield (np.ones((8, 4), "float32"), np.zeros((8, 2), "float32"))
            raise RuntimeError("loader died")

        with pytest.raises(RuntimeError, match="loader died"):
            m.fit(bad_batches(), epochs=1, verbose=0,
                  callbacks=[TelemetryCallback(monitor=mon)])
        assert current_monitor() is None
        assert m._monitor is None

    def test_aggregate_failure_never_aborts_fit(self, monkeypatch):
        """Eager cross-process collectives can be unsupported — telemetry
        must not crash a finished run, and teardown (active-monitor
        restore + model detach) must still happen."""
        mon = TrainMonitor()

        def boom(self):
            raise RuntimeError("eager cross-process all_reduce unsupported")

        monkeypatch.setattr(TrainMonitor, "aggregate", boom)
        m, cb = self._fit(mon, batches=1, epochs=1, aggregate_on_end=True)
        assert cb.last_aggregate is None
        assert current_monitor() is None
        assert m._monitor is None

    def test_train_batch_feeds_watchdog(self):
        from paddle_tpu.hapi import Model
        paddle.seed(6)
        m = Model(nn.Linear(4, 2), inputs=[None])
        m.prepare(Adam(0.01, parameters=m.parameters()), nn.MSELoss())
        mon = TrainMonitor()
        m._monitor = mon
        x = np.ones((4, 4), "float32")
        y = np.zeros((4, 2), "float32")
        m.train_batch([x], [y])            # call 1 = the hapi compile event
        (loss,) = m.train_batch([x], [y])
        assert mon.events("compile") and mon.events("train_step") \
            and mon.events("sync")
        assert mon.summary()["watchdog"]["last_loss"] == pytest.approx(loss)


class TestDistributedBuilders:
    def test_localsgd_step_monitor(self):
        from jax.sharding import Mesh
        from paddle_tpu.distributed.localsgd import make_localsgd_train_step
        devs = np.array(jax.devices()[:2])
        mesh = Mesh(devs, ("data",))
        params0 = {"w": jnp.ones((4,), jnp.float32)}

        def loss_of(params, x):
            return jnp.mean((x @ jnp.ones((4, 4)) @ params["w"]) ** 2)

        opt = Momentum(learning_rate=0.05, momentum=0.0)
        mon = TrainMonitor()
        step, state = make_localsgd_train_step(loss_of, params0, opt, mesh,
                                               k_steps=2, monitor=mon)
        x = jnp.ones((4, 4), jnp.float32)
        for _ in range(3):
            state, loss = step(state, 0.05, x)
        evs = mon.events("train_step")
        assert len(evs) == 2                   # first call = compile event
        assert all(e["trainer"] == "localsgd" for e in evs)
        assert mon.summary()["compile"]["misses"] == 1

    def test_gpt_train_step_monitor(self):
        from paddle_tpu.models.gpt import GPTConfig, GPTModel, \
            make_gpt_train_step
        from paddle_tpu.distributed import fleet
        paddle.seed(7)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2,
                        num_attention_heads=2, max_position_embeddings=16,
                        compute_dtype="float32")
        model = GPTModel(cfg)
        mon = TrainMonitor()
        step, state = make_gpt_train_step(
            model, Adam(1e-3, parameters=model.parameters()), hcg,
            remat=False, monitor=mon)
        x = jnp.zeros((2, 8), jnp.int32)
        y = jnp.zeros((2, 8), jnp.int32)
        for i in range(2):                     # call 1 = compile event
            state, loss = step(state, jax.random.key(i), np.float32(1e-3),
                               x, y)
        ev = mon.events("train_step")[-1]
        assert ev["trainer"] == "gpt"
        assert ev["examples"] == 2 and ev["tokens"] == 16


class TestProfilerStep:
    def test_num_samples_items_per_sec(self):
        from paddle_tpu.profiler import Profiler
        prof = Profiler(timer_only=True)
        prof.start()
        for _ in range(3):
            prof.step(num_samples=32)
        prof.stop()
        info = prof.step_info()
        assert "steps=3" in info and "ips=" in info
        # without samples the field stays absent
        p2 = Profiler(timer_only=True)
        p2.start()
        p2.step()
        p2.stop()
        assert "ips=" not in p2.step_info()

    def test_routes_into_active_monitor(self):
        from paddle_tpu.profiler import Profiler
        mon = TrainMonitor()
        with mon:
            assert current_monitor() is mon
            prof = Profiler(timer_only=True)
            prof.start()
            for _ in range(2):
                prof.step(num_samples=4)
            prof.stop()
        assert current_monitor() is None
        # profiler spans ride their OWN kind/counters so an instrumented
        # loop paced by Profiler.step never double-counts train_steps
        assert mon.events("train_step") == []
        evs = mon.events("profiler_step")
        assert len(evs) == 2
        assert all(e["examples"] == 4 for e in evs)
        assert mon.registry.value("profiler_steps") == 2
        assert mon.summary()["steps"] == 0


class TestExports:
    def test_jsonl_prometheus_roundtrip(self, tmp_path):
        mon = TrainMonitor()
        mon.record_step(0.01, trainer="t", examples=2, tokens=8)
        mon.record_sync(0.001, loss=1.5)
        mon.observe_scaler(8.0, found_inf=True)
        mon.hbm_census()
        path = tmp_path / "train.jsonl"
        n = mon.dump_jsonl(str(path))
        lines = [json.loads(ln) for ln in
                 path.read_text().splitlines() if ln]
        assert len(lines) == n
        kinds = {ln["kind"] for ln in lines}
        assert {"train_step", "sync", "amp", "hbm"} <= kinds
        # offline conversion == live conversion (the trace_to_chrome merge
        # contract for training dumps)
        assert chrome_trace_from_jsonl(str(path)) == mon.to_chrome_trace()
        text = mon.prometheus_text()
        vals = {ln.split()[0]: ln.split()[1] for ln in text.splitlines()
                if ln and not ln.startswith("#") and "{" not in ln}
        assert int(vals["paddle_tpu_train_train_steps"]) == 1
        assert int(vals["paddle_tpu_train_train_tokens"]) == 8
        assert int(vals["paddle_tpu_train_amp_found_inf"]) == 1
        assert "paddle_tpu_train_step_seconds_count" in vals

    def test_chrome_train_rows(self):
        mon = TrainMonitor()
        mon.record_step(0.02, trainer="t")
        mon.observe_loss(float("nan"))
        ct = mon.to_chrome_trace()
        train = [e for e in ct["traceEvents"]
                 if e.get("pid") == "paddle_tpu.train"]
        assert any(e["ph"] == "X" and e["name"] == "train_step"
                   for e in train)
        assert any(e["ph"] == "i" and e["name"] == "watchdog:non_finite"
                   for e in train)
