"""Goodput ledger (ISSUE 8): exhaustive wall-clock attribution.

The tentpole contracts under test: buckets sum to elapsed wall time within
1% (exhaustiveness — `unattributed` is the honest remainder, over-
attribution surfaces as `overflow_s`), the instrumentation seams (hapi
fit, DataLoader, reader.buffered, checkpoint io, fleet metrics) report
through the active ledger with zero cost when none is active (identical
lowering with and without), and the flight recorder dumps the telemetry
state on a raised exception."""

import json
import signal
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit.functional import make_train_step
from paddle_tpu.optimizer import Adam, Momentum
from paddle_tpu.telemetry import Tracer, TrainMonitor
from paddle_tpu.telemetry_ledger import (BUCKETS, FlightRecorder, RunLedger,
                                         chrome_counters_from_dump,
                                         current_ledger, ledger_span,
                                         set_active_ledger)


def _sum_ok(snap, tol=0.01):
    total = sum(snap["buckets_s"].values())
    elapsed = snap["elapsed_s"]
    return abs(total - elapsed) <= tol * elapsed + 1e-9


class TestRunLedgerCore:
    def test_buckets_sum_to_elapsed_exactly(self):
        led = RunLedger()
        led.record("compute", 0.005)          # attribution < real elapsed
        led.record("data_wait", 0.004)
        time.sleep(0.02)
        snap = led.snapshot()
        assert set(snap["buckets_s"]) == set(BUCKETS)
        assert _sum_ok(snap, tol=0.0)          # exact by construction
        assert snap["overflow_s"] == 0.0
        assert snap["buckets_s"]["unattributed"] > 0
        assert snap["goodput"] == pytest.approx(
            0.005 / snap["elapsed_s"], rel=1e-6)
        assert snap["counts"]["compute"] == 1

    def test_overflow_is_reported_not_hidden(self):
        led = RunLedger()
        led.record("compute", 1e6)             # absurd over-attribution
        snap = led.snapshot()
        assert snap["overflow_s"] > 0
        assert snap["buckets_s"]["unattributed"] == 0.0
        # the sum exceeds elapsed by EXACTLY the reported overflow
        assert sum(snap["buckets_s"].values()) == pytest.approx(
            snap["elapsed_s"] + snap["overflow_s"], rel=1e-9)

    def test_unknown_bucket_raises(self):
        led = RunLedger()
        with pytest.raises(ValueError):
            led.record("gpu_time", 1.0)
        with pytest.raises(ValueError):
            with led.span("nonsense"):
                pass
        with pytest.raises(ValueError):
            led.record("unattributed", 1.0)    # derived, never recorded

    def test_span_and_exclusive_absorption(self):
        led = RunLedger()
        with led.span("eval", exclusive=True):
            led.record("data_wait", 5.0)       # absorbed: inside eval
            led.record("eval", 0.001)          # same bucket passes through
            time.sleep(0.01)
        snap = led.snapshot()
        assert snap["buckets_s"]["data_wait"] == 0.0
        assert snap["buckets_s"]["eval"] >= 0.011
        # absorption is per-thread: another thread's records pass through
        done = threading.Event()

        def other():
            led.record("comm", 0.5)
            done.set()

        with led.span("eval", exclusive=True):
            t = threading.Thread(target=other)
            t.start()
            assert done.wait(5)
            t.join()
        assert led.snapshot()["buckets_s"]["comm"] == 0.5

    def test_close_freezes_and_drops(self):
        led = RunLedger()
        led.record("compute", 0.1)
        led.close()
        e1 = led.snapshot()["elapsed_s"]
        led.record("compute", 9.9)             # dropped: run is over
        time.sleep(0.01)
        snap = led.snapshot()
        assert snap["elapsed_s"] == e1 and snap["closed"]
        assert snap["buckets_s"]["compute"] == pytest.approx(0.1)

    def test_reset_restarts_clock(self):
        led = RunLedger()
        led.record("compute", 0.5)
        time.sleep(0.01)
        led.reset()
        snap = led.snapshot()
        assert snap["buckets_s"]["compute"] == 0.0
        assert snap["elapsed_s"] < 0.01

    def test_capacity_bounds_series_not_totals(self):
        led = RunLedger(capacity=4)
        for _ in range(10):
            led.record("compute", 0.01)
        d = led.to_dict()
        assert len(d["series"]) == 4
        assert d["snapshot"]["buckets_s"]["compute"] == pytest.approx(0.1)

    def test_prometheus_text(self):
        led = RunLedger()
        led.record("compute", 0.2)
        txt = led.prometheus_text()
        assert "paddle_tpu_ledger_goodput" in txt
        assert "paddle_tpu_ledger_compute_seconds 0.2" in txt
        assert "# TYPE paddle_tpu_ledger_compute_events counter" in txt

    def test_chrome_counters_cumulative(self, tmp_path):
        led = RunLedger()
        led.record("compute", 0.1)
        led.record("compute", 0.2)
        led.record("data_wait", 0.3)
        evs = led.to_chrome_counters()
        counters = [e for e in evs if e.get("ph") == "C"]
        assert len(counters) == 3
        assert counters[-1]["args"]["compute"] == pytest.approx(0.3)
        assert counters[-1]["args"]["data_wait"] == pytest.approx(0.3)
        assert [e["ts"] for e in counters] == sorted(
            e["ts"] for e in counters)
        # offline twin: dump_json -> chrome_counters_from_dump round-trips
        p = tmp_path / "ledger.json"
        led.dump_json(str(p))
        off = chrome_counters_from_dump(json.loads(p.read_text()))
        assert [e.get("args") for e in off if e.get("ph") == "C"] == \
            [e["args"] for e in counters]

    def test_aggregate_single_process_identity(self):
        led = RunLedger()
        led.record("compute", 0.4)
        led.record("comm", 0.1)
        agg = led.aggregate()
        assert agg["world"] == 1
        assert agg["buckets_s"]["compute"] == pytest.approx(0.4)
        # goodput over the aggregate's OWN elapsed (the clock keeps ticking
        # between calls, so a later snapshot would disagree slightly)
        assert agg["goodput"] == pytest.approx(
            0.4 / agg["elapsed_s_max"], rel=1e-6)
        assert agg["straggler_skew"]["compute"] == pytest.approx(1.0)
        assert agg["straggler_skew"]["checkpoint_save"] is None  # empty


class TestActiveLedgerSeams:
    def test_active_slot_install_restore(self):
        assert current_ledger() is None
        led = RunLedger()
        with led:
            assert current_ledger() is led
            inner = RunLedger()
            with inner:
                assert current_ledger() is inner
            assert current_ledger() is led
        assert current_ledger() is None

    def test_ledger_span_noop_when_inactive(self):
        with ledger_span("compute") as led:
            assert led is None

    def test_dataloader_prefetch_data_wait(self):
        from paddle_tpu.io import DataLoader, TensorDataset
        ds = TensorDataset([np.arange(32, dtype="float32").reshape(8, 4)])
        led = RunLedger()
        with led:
            batches = list(DataLoader(ds, batch_size=2))
        assert len(batches) == 4
        snap = led.snapshot()
        assert snap["counts"]["data_wait"] >= 4
        # and OFF path records nothing
        led2 = RunLedger()
        list(DataLoader(ds, batch_size=2))
        assert led2.snapshot()["counts"]["data_wait"] == 0

    def test_dataloader_sync_path_data_wait(self):
        from paddle_tpu.io import DataLoader, TensorDataset
        ds = TensorDataset([np.arange(32, dtype="float32").reshape(8, 4)])
        led = RunLedger()
        with led:
            batches = list(DataLoader(ds, batch_size=4,
                                      use_buffer_reader=False))
        assert len(batches) == 2
        assert led.snapshot()["counts"]["data_wait"] == 2

    def test_reader_buffered_data_wait(self):
        from paddle_tpu.reader import buffered

        def r():
            yield from range(5)

        led = RunLedger()
        with led:
            out = list(buffered(r, 2)())
        assert out == list(range(5))
        assert led.snapshot()["counts"]["data_wait"] >= 5

    def test_framework_io_checkpoint_spans(self, tmp_path):
        from paddle_tpu.framework import io as fio
        led = RunLedger()
        path = str(tmp_path / "m.pdparams")
        with led:
            fio.save({"w": np.ones((4, 4), "float32")}, path)
            fio.load(path)
        snap = led.snapshot()
        assert snap["counts"]["checkpoint_save"] == 1
        assert snap["counts"]["checkpoint_restore"] == 1
        assert snap["buckets_s"]["checkpoint_save"] > 0

    def test_distributed_checkpoint_spans(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as ckpt
        state = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        led = RunLedger()
        with led:
            ckpt.save(state, str(tmp_path / "ck"))
            out = ckpt.load(str(tmp_path / "ck"), target=state)
        np.testing.assert_allclose(np.asarray(out["w"]), np.ones((4, 4)))
        snap = led.snapshot()
        assert snap["counts"]["checkpoint_save"] == 1
        assert snap["counts"]["checkpoint_restore"] == 1

    def test_fleet_metrics_comm_span(self):
        from paddle_tpu.distributed.fleet.metrics.metric import \
            all_reduce_metrics
        led = RunLedger()
        with led:
            out = all_reduce_metrics({"a": 1.0, "b": 2.0}, "sum")
        assert out == {"a": 1.0, "b": 2.0}
        assert led.snapshot()["counts"]["comm"] == 1


class TestMonitorForwarding:
    def test_monitor_events_map_to_buckets(self):
        mon = TrainMonitor()
        led = RunLedger()
        mon.set_ledger(led)
        mon.record_compile(("step",), 0.5)
        mon.record_step(0.2, trainer="t", examples=2)
        mon.record_sync(0.1, loss=1.0)
        mon.record_profiler_step(9.0)          # deliberately NOT forwarded
        snap = led.snapshot()
        assert snap["buckets_s"]["compile"] == pytest.approx(0.5)
        assert snap["buckets_s"]["host_dispatch"] == pytest.approx(0.2)
        assert snap["buckets_s"]["compute"] == pytest.approx(0.1)
        # detach: nothing records afterwards
        mon.set_ledger(None)
        mon.record_step(5.0, trainer="t")
        assert led.snapshot()["buckets_s"]["host_dispatch"] == \
            pytest.approx(0.2)

    def test_tracer_tick_and_compile_feed_ledger(self):
        tr = Tracer()
        led = RunLedger()
        tr.set_ledger(led)
        tr.tick("Eng", 0.05, queue_depth=0)
        tr.compile_event("Eng", ("prefill", 8), hit=False, wall_s=0.3)
        tr.compile_event("Eng", ("prefill", 8), hit=True)   # hits don't
        snap = led.snapshot()
        assert snap["buckets_s"]["compute"] == pytest.approx(0.05)
        assert snap["buckets_s"]["compile"] == pytest.approx(0.3)
        assert snap["counts"]["compile"] == 1

    def test_in_tick_compile_wall_not_double_attributed(self):
        """A compile paid INSIDE a tick lands in ``compile`` only — the
        tick's compute attribution subtracts it, keeping the buckets
        non-overlapping (a cold serving engine would otherwise report
        attributed > elapsed and a fictitious goodput)."""
        tr = Tracer()
        led = RunLedger()
        tr.set_ledger(led)
        # tick bracketing a 0.4s compile: 0.5s wall, 0.1s real compute
        tr.compile_event("Eng", ("prefill", 8), hit=False, wall_s=0.4)
        tr.tick("Eng", 0.5, queue_depth=0)
        snap = led.snapshot()
        assert snap["buckets_s"]["compile"] == pytest.approx(0.4)
        assert snap["buckets_s"]["compute"] == pytest.approx(0.1)
        # compiles BETWEEN ticks (warmup) never reduce a later tick
        tr.compile_event("Eng", ("decode", 4), hit=False, wall_s=9.0)
        time.sleep(0.02)
        tr.tick("Eng", 0.01, queue_depth=0)
        snap = led.snapshot()
        assert snap["buckets_s"]["compute"] == pytest.approx(0.11, abs=1e-3)

    def test_engine_attach_ledger_requires_tracer(self):
        from paddle_tpu.models.gpt import GPTConfig, GPTModel
        from paddle_tpu.serving import ContinuousBatchingEngine
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_attention_heads=2, max_position_embeddings=32,
                        compute_dtype="float32")
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        eng = ContinuousBatchingEngine(model, params, max_slots=1,
                                       max_len=16, prompt_buckets=[8])
        with pytest.raises(ValueError):
            eng.attach_ledger(RunLedger())
        eng2 = ContinuousBatchingEngine(model, params, max_slots=1,
                                        max_len=16, prompt_buckets=[8],
                                        tracer=Tracer())
        led = eng2.attach_ledger(RunLedger())
        assert eng2.tracer._ledger is led

    def test_identical_lowering_with_and_without_ledger(self):
        """Off-path purity: the ledger observes host-side walls only — the
        compiled program is byte-identical with ledger attached or not."""
        def build(with_ledger):
            paddle.seed(3)
            layer = nn.Linear(4, 3)
            mon = TrainMonitor()
            if with_ledger:
                mon.set_ledger(RunLedger())
            step, state = make_train_step(
                layer, nn.MSELoss(),
                Momentum(learning_rate=0.1, momentum=0.9), monitor=mon)
            rest = (jax.random.key(0), np.float32(0.1),
                    [jnp.ones((8, 4))], [jnp.zeros((8, 3))])
            return step.lower(state, *rest).as_text()

        assert build(False) == build(True)


class TestFitIntegration:
    def _fit(self, callbacks, epochs=1, batches=6, eval_data=None):
        paddle.seed(0)
        from paddle_tpu.hapi import Model
        m = Model(nn.Linear(4, 2), inputs=[None])
        m.prepare(Adam(0.01, parameters=m.parameters()), nn.MSELoss())
        xs = np.ones((8, 4), "float32")
        ys = np.zeros((8, 2), "float32")
        m.fit([(xs, ys)] * batches, eval_data=eval_data, epochs=epochs,
              verbose=0, callbacks=callbacks)
        return m

    def test_goodput_callback_end_to_end(self, tmp_path):
        from paddle_tpu.callbacks import GoodputCallback
        path = str(tmp_path / "goodput.json")
        cb = GoodputCallback(json_path=path)
        m = self._fit([cb], epochs=2)
        snap = cb.last_snapshot
        assert snap is not None
        # THE acceptance invariant: buckets sum to elapsed wall within 1%
        assert _sum_ok(snap)
        assert snap["overflow_s"] == 0.0
        assert snap["buckets_s"]["compile"] > 0      # first dispatch
        assert snap["buckets_s"]["host_dispatch"] > 0
        assert snap["counts"]["compute"] >= 1        # log_freq loss fetch
        # teardown is symmetric: nothing active, monitor detached
        assert current_ledger() is None
        assert m._monitor is None
        assert json.loads(open(path).read())["snapshot"]["elapsed_s"] > 0

    def test_goodput_callback_reuses_existing_monitor(self):
        from paddle_tpu.callbacks import GoodputCallback, TelemetryCallback
        tele = TelemetryCallback()
        good = GoodputCallback()
        self._fit([tele, good])
        assert good.monitor is tele.monitor
        assert good.last_snapshot["buckets_s"]["host_dispatch"] > 0
        assert tele.monitor.tracer._ledger is None   # detached at end

    def test_eval_lands_in_eval_bucket(self):
        from paddle_tpu.callbacks import GoodputCallback
        cb = GoodputCallback()
        xs = np.ones((8, 4), "float32")
        ys = np.zeros((8, 2), "float32")
        self._fit([cb], eval_data=[(xs, ys)] * 3)
        snap = cb.last_snapshot
        assert snap["buckets_s"]["eval"] > 0
        assert _sum_ok(snap)


class TestFlightRecorder:
    def _recorder(self, tmp_path):
        mon = TrainMonitor()
        mon.record_step(0.01, trainer="t", examples=1)
        led = RunLedger()
        led.record("compute", 0.2)
        return FlightRecorder(str(tmp_path / "crash"),
                              sources=[mon, led]), mon, led

    def test_dump_on_raised_exception(self, tmp_path):
        fr, mon, led = self._recorder(tmp_path)
        prev_hook = sys.excepthook
        fr.install(signals=())
        try:
            assert sys.excepthook is not prev_hook
            try:
                raise RuntimeError("boom")
            except RuntimeError:
                # what the interpreter does on an unhandled exception
                sys.excepthook(*sys.exc_info())
        finally:
            fr.uninstall()
        assert sys.excepthook is prev_hook
        dumps = list((tmp_path / "crash").glob("crash-*"))
        assert len(dumps) == 1
        out = dumps[0]
        meta = json.loads((out / "meta.json").read_text())
        assert "RuntimeError" in meta["reason"]
        threads = (out / "threads.txt").read_text()
        assert "Thread" in threads or "File" in threads
        # the monitor's ring buffer survived as JSONL
        jsonl = (out / "trainmonitor0.jsonl").read_text().splitlines()
        assert any(json.loads(l)["kind"] == "train_step" for l in jsonl)
        # the ledger snapshot survived
        ldump = json.loads((out / "runledger1.json").read_text())
        assert ldump["snapshot"]["buckets_s"]["compute"] == \
            pytest.approx(0.2)

    def test_signal_dump_chains_previous_handler(self, tmp_path):
        fr, _, _ = self._recorder(tmp_path)
        hit = []
        prev = signal.signal(signal.SIGUSR1, lambda s, f: hit.append(s))
        try:
            fr.install(signals=(signal.SIGUSR1,), enable_faulthandler=False)
            signal.raise_signal(signal.SIGUSR1)
            assert hit == [signal.SIGUSR1]       # chained, process alive
            assert list((tmp_path / "crash").glob("crash-*"))
        finally:
            fr.uninstall()
            signal.signal(signal.SIGUSR1, prev)

    def test_auto_dump_once_manual_dumps_unique(self, tmp_path):
        """Two automatic triggers for one death keep the FIRST dump;
        manual dumps always land, each in its own directory (same-second
        stamps must not overwrite)."""
        fr, _, _ = self._recorder(tmp_path)
        assert fr.dump("first", _auto=True) is not None
        assert fr.dump("second", _auto=True) is None   # deduped
        d1 = fr.dump("manual-1")
        d2 = fr.dump("manual-2")
        assert d1 is not None and d2 is not None and d1 != d2
        assert len(list((tmp_path / "crash").glob("crash-*"))) == 3

    def test_dump_never_raises(self, tmp_path):
        class Bad:
            def dump_jsonl(self, path):
                raise OSError("disk gone")

        fr = FlightRecorder(str(tmp_path / "crash"), sources=[Bad()])
        out = fr.dump("manual")
        assert out is not None                   # partial dump still lands
        assert (tmp_path / "crash").exists()

    def test_bad_source_rejected(self, tmp_path):
        with pytest.raises(TypeError):
            FlightRecorder(str(tmp_path), sources=[object()])

    def test_add_source_races_dump_guard_clean(self, tmp_path,
                                               lock_sanitizer):
        """Regression for the unlocked ``_sources`` list: ``dump()`` runs
        on signal/excepthook paths and used to iterate the list bare
        while the main thread was still ``add_source``-ing — the crash
        handler could tear mid-append and destroy the evidence.  The
        sanitizer harvests the ``# guarded-by: _sources_lock``
        declaration, so the snapshot-under-lock discipline is checked at
        every access while dumps and attaches genuinely overlap."""
        fr = FlightRecorder(str(tmp_path / "crash"))
        wired = lock_sanitizer.instrument_guards(fr)
        assert ("_sources", "_sources_lock") in wired
        errors, stop = [], threading.Event()

        def dumper():
            try:
                i = 0
                while not stop.is_set():
                    fr.dump(f"overlap-{i}")
                    i += 1
            except Exception as e:  # noqa: BLE001 — repro harness
                errors.append(e)

        t = threading.Thread(target=dumper, name="dumper")
        t.start()
        try:
            for i in range(20):
                led = RunLedger()
                led.record("compute", 0.01)
                fr.add_source(led, f"src{i}")
        finally:
            stop.set()
            t.join()
        assert not errors, errors
        assert fr.dump("final") is not None      # all 20 attached


class TestFitExceptionTeardown:
    def test_raise_mid_fit_never_leaks_active_ledger(self):
        """A raise skips GoodputCallback.on_train_end — Model.fit's finally
        must still clear the active ledger and the monitor forwarding."""
        from paddle_tpu.callbacks import Callback, GoodputCallback
        from paddle_tpu.hapi import Model

        class Boom(Callback):
            def on_train_batch_end(self, step, logs=None):
                if step >= 1:
                    raise RuntimeError("boom")

        paddle.seed(0)
        m = Model(nn.Linear(4, 2), inputs=[None])
        m.prepare(Adam(0.01, parameters=m.parameters()), nn.MSELoss())
        cb = GoodputCallback()
        xs = np.ones((8, 4), "float32")
        ys = np.zeros((8, 2), "float32")
        with pytest.raises(RuntimeError):
            m.fit([(xs, ys)] * 4, epochs=1, verbose=0,
                  callbacks=[cb, Boom()])
        assert current_ledger() is None
        assert cb.monitor.tracer._ledger is None
