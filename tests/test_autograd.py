"""Autograd engine tests — numeric-gradient oracle in the reference's OpTest
style (op_test.py:1450 check_grad)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def numeric_grad(fn, x, eps=1e-3):
    g = np.zeros_like(x)
    flat = x.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        hi = fn(x)
        flat[i] = orig - eps
        lo = fn(x)
        flat[i] = orig
        gf[i] = (hi - lo) / (2 * eps)
    return g


@pytest.mark.parametrize("op,np_op", [
    (lambda t: (t.exp()).sum(), lambda a: np.exp(a).sum()),
    (lambda t: (t.tanh()).sum(), lambda a: np.tanh(a).sum()),
    (lambda t: (t * t + 2 * t).sum(), lambda a: (a * a + 2 * a).sum()),
    (lambda t: (t.sigmoid()).sum(), lambda a: (1 / (1 + np.exp(-a))).sum()),
    (lambda t: (t.reshape([6]) ** 2).sum(), lambda a: (a.reshape(6) ** 2).sum()),
])
def test_grad_vs_numeric(op, np_op):
    x = np.random.randn(2, 3).astype("float64")
    t = paddle.to_tensor(x, stop_gradient=False)
    op(t).backward()
    ng = numeric_grad(lambda a: float(np_op(a)), x.copy())
    np.testing.assert_allclose(t.grad.numpy(), ng, rtol=1e-4, atol=1e-4)


def test_matmul_grad():
    a = np.random.randn(3, 4).astype("float32")
    b = np.random.randn(4, 5).astype("float32")
    ta = paddle.to_tensor(a, stop_gradient=False)
    tb = paddle.to_tensor(b, stop_gradient=False)
    (ta @ tb).sum().backward()
    np.testing.assert_allclose(ta.grad.numpy(), np.ones((3, 5)) @ b.T, rtol=1e-5)
    np.testing.assert_allclose(tb.grad.numpy(), a.T @ np.ones((3, 5)), rtol=1e-5)


def test_grad_accumulation():
    t = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    (t * 2).sum().backward()
    (t * 3).sum().backward()
    np.testing.assert_allclose(t.grad.numpy(), [5.0, 5.0])
    t.clear_grad()
    assert t.grad is None


def test_no_grad():
    t = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        u = t * 2
    assert u.stop_gradient


def test_partial_grad_api():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x
    z = y * 3
    (gy,) = paddle.grad(z, [y])
    np.testing.assert_allclose(gy.numpy(), [3.0])
    assert x._grad is None  # paddle.grad must not pollute leaf grads


def test_inplace_aliasing():
    a = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    b = a * 3
    c = b + 1
    b[0] = 0.0
    (b.sum() + c.sum()).backward()
    np.testing.assert_allclose(a.grad.numpy(), [3.0, 6.0])


def test_diamond():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x
    (y + y).backward()
    np.testing.assert_allclose(x.grad.numpy(), [8.0])


def test_hook():
    t = paddle.to_tensor([1.0], stop_gradient=False)
    t.register_hook(lambda g: g * 10)
    (t * 2).backward()
    np.testing.assert_allclose(t.grad.numpy(), [20.0])


def test_detach_stops_grad():
    t = paddle.to_tensor([1.0], stop_gradient=False)
    u = (t * 2).detach() * 3
    assert u.stop_gradient


def test_eager_loop_perf_nudge_warns_once():
    """A long grad-recording eager streak with no jit step must produce ONE
    UserWarning nudge (VERDICT r3 weak #5); a traced dispatch resets the
    streak, and FLAGS_eager_nudge_after=0 disables the counter."""
    import warnings

    import jax
    import jax.numpy as jnp

    from paddle_tpu.core import flags, tensor as tmod

    old = flags.flag("FLAGS_eager_nudge_after")
    old_streak = tmod._EAGER_STREAK[0]
    try:
        flags.set_flags({"FLAGS_eager_nudge_after": 10})
        tmod._EAGER_STREAK[0] = 0
        x = paddle.to_tensor([1.0], stop_gradient=False)
        with warnings.catch_warnings(record=True) as got:
            warnings.simplefilter("always")
            for _ in range(25):
                x * 2
        msgs = [w for w in got if "consecutive eagerly-dispatched"
                in str(w.message)]
        assert len(msgs) == 1  # warn once, not on every dispatch past N

        # a jit'd step resets the streak
        tmod._EAGER_STREAK[0] = 0
        for _ in range(5):
            x * 2
        jitted = jax.jit(lambda a: (paddle.to_tensor(a, stop_gradient=False)
                                    * 2)._data)
        jitted(jnp.ones(1))
        assert tmod._EAGER_STREAK[0] == 0

        # compiled-step CACHE HITS reset it too (no eager dispatch happens
        # on a cache hit, so the reset must come from the step wrapper)
        from paddle_tpu.jit.functional import make_eval_step
        import paddle_tpu.nn as nn
        lin = nn.Linear(2, 2)
        estep = make_eval_step(lin)
        p, b = lin.raw_state()
        estep(p, b, (jnp.ones((1, 2)),))       # compile
        for _ in range(5):
            x * 2
        assert tmod._EAGER_STREAK[0] == 5
        estep(p, b, (jnp.ones((1, 2)),))       # cache hit
        assert tmod._EAGER_STREAK[0] == 0

        # 0 disables
        flags.set_flags({"FLAGS_eager_nudge_after": 0})
        tmod._EAGER_STREAK[0] = 0
        with warnings.catch_warnings(record=True) as got:
            warnings.simplefilter("always")
            for _ in range(25):
                x * 2
        assert not [w for w in got if "consecutive" in str(w.message)]
    finally:
        flags.set_flags({"FLAGS_eager_nudge_after": old})
        tmod._EAGER_STREAK[0] = old_streak
