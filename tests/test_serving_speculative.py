"""Speculative continuous batching (SpeculativeBatchingEngine): draft
proposals + one verify chunk per round, per-slot acceptance — outputs must
be BIT-LOSSLESS vs the plain engine (greedy acceptance takes the longest
argmax-matching prefix, the models/_decode.py speculative contract), while
a good draft cuts the round count."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTConfig, GPTModel
from paddle_tpu.serving import (ContinuousBatchingEngine,
                                SpeculativeBatchingEngine)


@pytest.fixture(scope="module")
def models():
    paddle.seed(31)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=3,
                    num_attention_heads=4, max_position_embeddings=96,
                    compute_dtype="float32")
    target = GPTModel(cfg)
    tparams = {n: p._data for n, p in target.named_parameters()}
    dcfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=1,
                     num_attention_heads=4, max_position_embeddings=96,
                     compute_dtype="float32")
    draft = GPTModel(dcfg)
    dparams = {n: p._data for n, p in draft.named_parameters()}
    return target, tparams, draft, dparams


PROMPTS = [[5, 17, 3], [40, 2], [9, 9, 9, 9, 1], [61, 8, 30]]
BUDGETS = [12, 6, 9, 4]


class TestSpeculativeEngine:
    def test_lossless_vs_plain_engine(self, models):
        """Random 1-layer draft: every request's tokens equal the plain
        engine's (which equal solo generate) — acceptance only changes how
        fast, never what."""
        target, tparams, draft, dparams = models
        plain = ContinuousBatchingEngine(target, tparams, max_slots=2,
                                         max_len=48, prompt_buckets=[8])
        prids = [plain.add_request(p, n) for p, n in zip(PROMPTS, BUDGETS)]
        want = plain.run_to_completion(max_ticks=300)

        spec = SpeculativeBatchingEngine(target, tparams, draft, dparams,
                                         max_slots=2, max_len=48,
                                         draft_k=3, prompt_buckets=[8])
        srids = [spec.add_request(p, n) for p, n in zip(PROMPTS, BUDGETS)]
        got = spec.run_to_completion(max_ticks=300)
        for pr, sr in zip(prids, srids):
            assert got[sr] == want[pr], "speculative engine is not lossless"

    def test_perfect_draft_round_count(self, models):
        """Draft == target: every proposal accepted, so one request of N
        tokens finishes in ceil((N-1)/(K+1)) rounds after admission — the
        observable that catches silent acceptance degradation (the
        round-3 draft-cache-hole bug class)."""
        target, tparams, _, _ = models
        K, N = 3, 13
        spec = SpeculativeBatchingEngine(target, tparams, target, tparams,
                                         max_slots=1, max_len=48,
                                         draft_k=K, prompt_buckets=[8])
        rid = spec.add_request(PROMPTS[0], N)
        got = spec.run_to_completion(max_ticks=100)
        assert len(got[rid]) == N
        assert spec.rounds == -(-(N - 1) // (K + 1)), \
            (spec.rounds, N, K)

    def test_eos_retires_and_slot_reuse_stays_lossless(self, models):
        """EOS mid-round discards the accepted tail; the freed slot's next
        occupant (on both caches) still matches the plain engine."""
        target, tparams, draft, dparams = models
        probe = ContinuousBatchingEngine(target, tparams, max_slots=1,
                                         max_len=48, prompt_buckets=[8])
        pid = probe.add_request(PROMPTS[0], 10)
        full = probe.run_to_completion(max_ticks=100)[pid]
        eos = full[4]
        cut = full.index(eos) + 1

        spec = SpeculativeBatchingEngine(target, tparams, draft, dparams,
                                         max_slots=1, max_len=48,
                                         draft_k=3, prompt_buckets=[8],
                                         eos_token_id=int(eos))
        r0 = spec.add_request(PROMPTS[0], 10)
        r1 = spec.add_request(PROMPTS[3], 4)
        got = spec.run_to_completion(max_ticks=200)
        assert got[r0] == full[:cut]
        solo = target.generate(tparams, jnp.asarray([PROMPTS[3]], jnp.int32),
                               4, greedy=True)
        assert got[r1] == [int(t) for t in np.asarray(solo)[0]]

    def test_mid_flight_admission_isolated(self, models):
        """A request admitted while another is mid-speculation must not
        perturb it (slot isolation under variable per-row advance)."""
        target, tparams, draft, dparams = models
        spec = SpeculativeBatchingEngine(target, tparams, draft, dparams,
                                         max_slots=2, max_len=48,
                                         draft_k=3, prompt_buckets=[8])
        r0 = spec.add_request(PROMPTS[0], 12)
        for _ in range(2):
            spec.step()
        r1 = spec.add_request(PROMPTS[1], 6)
        got = spec.run_to_completion(max_ticks=200)
        for rid, p, n in ((r0, PROMPTS[0], 12), (r1, PROMPTS[1], 6)):
            solo = target.generate(tparams, jnp.asarray([p], jnp.int32), n,
                                   greedy=True)
            assert got[rid] == [int(t) for t in np.asarray(solo)[0]]

    def test_budget_includes_overproposal_slack(self, models):
        target, tparams, draft, dparams = models
        spec = SpeculativeBatchingEngine(target, tparams, draft, dparams,
                                         max_slots=1, max_len=20,
                                         draft_k=4, prompt_buckets=[8])
        with pytest.raises(ValueError, match="exceeds max_len"):
            spec.add_request([1, 2, 3], 10)   # 8 + 10 + 3 > 20
        spec.add_request([1, 2, 3], 9)        # 8 + 9 + 3 == 20: fits
        spec.add_request([1, 2, 3], 1)        # budget 1: prefill only,
        # no round runs, so no over-proposal slack is charged

    def test_draft_validation(self, models):
        target, tparams, _, _ = models
        paddle.seed(9)
        bad_vocab = GPTModel(GPTConfig(
            vocab_size=50, hidden_size=16, num_layers=1,
            num_attention_heads=4, max_position_embeddings=96,
            compute_dtype="float32"))
        bv = {n: p._data for n, p in bad_vocab.named_parameters()}
        with pytest.raises(ValueError, match="vocab"):
            SpeculativeBatchingEngine(target, tparams, bad_vocab, bv,
                                      max_slots=1, max_len=32,
                                      prompt_buckets=[8])
        short_pos = GPTModel(GPTConfig(
            vocab_size=97, hidden_size=16, num_layers=1,
            num_attention_heads=4, max_position_embeddings=16,
            compute_dtype="float32"))
        sp = {n: p._data for n, p in short_pos.named_parameters()}
        with pytest.raises(ValueError, match="DRAFT"):
            SpeculativeBatchingEngine(target, tparams, short_pos, sp,
                                      max_slots=1, max_len=32,
                                      prompt_buckets=[8])


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1])
def test_speculative_fuzz_matches_solo(models, seed):
    """Randomized speculative scenarios (draft_k, slots, budgets, staggered
    admission, optional EOS): every request equals solo greedy generate —
    the lossless claim under scheduler composition."""
    target, tparams, draft, dparams = models
    rng = np.random.RandomState(100 + seed)
    K = int(rng.choice([1, 2, 4]))
    eos = int(rng.randint(0, 97)) if rng.rand() < 0.5 else None
    spec = SpeculativeBatchingEngine(
        target, tparams, draft, dparams, max_slots=int(rng.randint(1, 4)),
        max_len=48, draft_k=K, prompt_buckets=[8],
        eos_token_id=eos)
    reqs = []
    for _ in range(int(rng.randint(3, 7))):
        p = [int(t) for t in rng.randint(1, 97, rng.randint(1, 9))]
        n = int(rng.randint(1, 12))
        reqs.append((spec.add_request(p, n), p, n))
        for _ in range(int(rng.randint(0, 3))):
            spec.step()
    got = spec.run_to_completion(max_ticks=500)
    for rid, p, n in reqs:
        solo = target.generate(tparams, jnp.asarray([p], jnp.int32), n,
                               greedy=True)
        want = [int(t) for t in np.asarray(solo)[0]]
        if eos is not None and eos in want:
            want = want[:want.index(eos) + 1]
        assert got[rid] == want, (seed, rid, K, eos)


def test_speculative_engine_int8_target(models):
    """Speculative batching over an int8-cache target (and fp draft): the
    quantized pair flows through the verify chunk's tuple-dispatch writes;
    outputs equal the int8 model's own solo generation."""
    from paddle_tpu.models.gpt import GPTConfig, GPTModel
    paddle.seed(31)   # same seed as the fixture target: identical weights
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=3,
                    num_attention_heads=4, max_position_embeddings=96,
                    compute_dtype="float32", kv_cache_dtype="int8")
    target = GPTModel(cfg)
    tparams = {n: p._data for n, p in target.named_parameters()}
    _, _, draft, dparams = models
    spec = SpeculativeBatchingEngine(target, tparams, draft, dparams,
                                     max_slots=2, max_len=48, draft_k=3,
                                     prompt_buckets=[8])
    rids = [spec.add_request(p, n) for p, n in zip(PROMPTS[:3], (8, 5, 7))]
    got = spec.run_to_completion(max_ticks=200)
    assert spec.caches[0][0].dtype == jnp.int8
    for rid, p, n in zip(rids, PROMPTS[:3], (8, 5, 7)):
        solo = target.generate(tparams, jnp.asarray([p], jnp.int32), n,
                               greedy=True)
        assert got[rid] == [int(t) for t in np.asarray(solo)[0]], rid


def test_cross_family_moe_target_gpt_draft(models):
    """The engine's draft and target only meet through the mixin contract:
    ERNIE-MoE target + GPT draft (the round-3 cross-family pairing, now on
    the batched scheduler) stays lossless vs the MoE's solo generation."""
    from paddle_tpu.models.ernie_moe import ErnieMoeConfig, ErnieMoeModel
    paddle.seed(41)
    cfg = ErnieMoeConfig(vocab_size=97, hidden_size=32, num_layers=2,
                         num_attention_heads=4, num_experts=4, top_k=2,
                         max_position_embeddings=96,
                         compute_dtype="float32")
    target = ErnieMoeModel(cfg)
    tparams = {n: p._data for n, p in target.named_parameters()}
    _, _, draft, dparams = models   # GPT 1-layer draft, same vocab
    spec = SpeculativeBatchingEngine(target, tparams, draft, dparams,
                                     max_slots=2, max_len=48, draft_k=3,
                                     prompt_buckets=[8])
    rids = [spec.add_request(p, n) for p, n in zip(PROMPTS[:3], (7, 5, 6))]
    got = spec.run_to_completion(max_ticks=200)
    for rid, p, n in zip(rids, PROMPTS[:3], (7, 5, 6)):
        solo = target.generate(tparams, jnp.asarray([p], jnp.int32), n,
                               greedy=True)
        assert got[rid] == [int(t) for t in np.asarray(solo)[0]], rid
