"""ZeRO stage 1/2/3 contractual tests on the virtual 8-device mesh.

Oracles (reference methodology, test_dist_base.py:1457):
- loss parity: each stage must reproduce the unsharded run bit-for-tolerance;
- memory contract: per-device optimizer-state bytes shrink ~1/shard;
- found_inf / dynamic loss scale: non-finite steps skip the update and back
  off the scale (check_finite_and_unscale + update_loss_scaling semantics);
- master weights: half params update through fp32 masters.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.zero import (make_zero_train_step,
                                         per_device_state_bytes)
from paddle_tpu.optimizer import Adam

needs8 = pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 devices")


def _mlp_params(seed=0, dtype=jnp.float32):
    r = np.random.RandomState(seed)
    mk = lambda *s: jnp.asarray(r.standard_normal(s).astype(np.float32) * 0.1,
                                dtype=dtype)
    return {"w1": mk(16, 32), "b1": mk(32), "w2": mk(32, 8), "b2": mk(8)}


def _loss_of(params, x, y):
    h = jnp.tanh(x @ params["w1"].astype(jnp.float32)
                 + params["b1"].astype(jnp.float32))
    logits = h @ params["w2"].astype(jnp.float32) + params["b2"].astype(jnp.float32)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))


def _mesh(sharding, dp=1):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": 1, "pp_degree": 1,
                               "sharding_degree": sharding}
    fleet.fleet.init(is_collective=True, strategy=strategy)
    return fleet.fleet.get_hybrid_communicate_group().mesh


def _batch(seed=1):
    r = np.random.RandomState(seed)
    return (jnp.asarray(r.standard_normal((16, 16)).astype(np.float32)),
            jnp.asarray(r.randint(0, 8, 16)))


@needs8
class TestZeroParity:
    @pytest.mark.parametrize("stage", [1, 2, 3])
    def test_loss_parity_vs_unsharded(self, stage):
        x, y = _batch()

        def run(sharding, st):
            mesh = _mesh(sharding)
            step, state = make_zero_train_step(
                _loss_of, _mlp_params(), Adam(1e-2), mesh, zero_stage=st)
            losses = []
            for _ in range(5):
                state, loss = step(state, np.float32(1e-2), x, y)
                losses.append(float(loss))
            return losses

        serial = run(1, 1)
        sharded = run(4, stage)
        np.testing.assert_allclose(serial, sharded, rtol=1e-5, atol=1e-6)

    def test_state_bytes_shrink(self):
        x, y = _batch()

        def bytes_at(sharding):
            mesh = _mesh(sharding)
            step, state = make_zero_train_step(
                _loss_of, _mlp_params(), Adam(1e-2), mesh, zero_stage=1)
            state, _ = step(state, np.float32(1e-2), x, y)
            return per_device_state_bytes(state)

        full = bytes_at(1)
        shard4 = bytes_at(4)
        # all params here have a 4-divisible dim → expect ~1/4
        assert shard4 <= full / 4 + 64, (full, shard4)

    def test_unshardable_param_warns(self):
        mesh = _mesh(4)
        params = _mlp_params()
        params["odd"] = jnp.ones((3, 3), jnp.float32)  # no 4-divisible dim
        with pytest.warns(UserWarning, match="no dim divisible"):
            make_zero_train_step(
                lambda p, x, y: _loss_of(p, x, y) + jnp.sum(p["odd"]) * 0.0,
                params, Adam(1e-2), mesh, zero_stage=3)


@needs8
class TestGPTZero:
    @pytest.mark.parametrize("stage", [2, 3])
    def test_gpt_parity_dp_x_sharding(self, stage):
        """Flagship path: GPT under dp2 x sharding4 ZeRO matches serial."""
        from paddle_tpu.models.gpt import GPTConfig, GPTModel, make_gpt_train_step
        from paddle_tpu.optimizer import AdamW

        x = jnp.asarray(np.random.RandomState(0).randint(0, 128, (8, 16)))
        y = jnp.asarray(np.random.RandomState(1).randint(0, 128, (8, 16)))

        def run(dp, sharding, st):
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": 1,
                                       "pp_degree": 1,
                                       "sharding_degree": sharding}
            fleet.fleet.init(is_collective=True, strategy=strategy)
            hcg = fleet.fleet.get_hybrid_communicate_group()
            paddle.seed(11)
            cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                            num_attention_heads=2, max_position_embeddings=32,
                            compute_dtype="float32")
            model = GPTModel(cfg)
            step, state = make_gpt_train_step(model, AdamW(1e-3), hcg,
                                              remat=False, zero_stage=st)
            losses = []
            for i in range(3):
                state, loss = step(state, jax.random.key(0), np.float32(1e-3),
                                   x, y)
                losses.append(float(loss))
            return losses

        serial = run(1, 1, 1)
        sharded = run(2, 4, stage)
        np.testing.assert_allclose(serial, sharded, rtol=2e-5, atol=1e-6)


@needs8
class TestLossScaling:
    def test_found_inf_skips_update_and_backs_off(self):
        mesh = _mesh(4)
        step, state = make_zero_train_step(
            _loss_of, _mlp_params(), Adam(1e-2), mesh, zero_stage=2,
            dynamic_loss_scale=True, init_loss_scale=1024.0)
        x, y = _batch()
        bad_x = x.at[0, 0].set(jnp.inf)
        p_before = jax.tree_util.tree_map(np.asarray, state["params"])
        state, loss = step(state, np.float32(1e-2), bad_x, y)
        assert bool(state["scaler"]["found_inf"])
        assert float(state["scaler"]["scale"]) == 512.0
        assert int(state["opt"]["step"]) == 0
        for k, v in state["params"].items():
            np.testing.assert_array_equal(np.asarray(v), p_before[k])
        # a following finite step proceeds normally
        state, loss = step(state, np.float32(1e-2), x, y)
        assert not bool(state["scaler"]["found_inf"])
        assert int(state["opt"]["step"]) == 1

    def test_scale_grows_after_interval(self):
        mesh = _mesh(4)
        step, state = make_zero_train_step(
            _loss_of, _mlp_params(), Adam(1e-2), mesh, zero_stage=1,
            dynamic_loss_scale=True, init_loss_scale=256.0, growth_interval=2)
        x, y = _batch()
        for _ in range(2):
            state, _ = step(state, np.float32(1e-2), x, y)
        assert float(state["scaler"]["scale"]) == 512.0
        assert int(state["scaler"]["good_steps"]) == 0


@needs8
class TestMasterWeights:
    def test_bf16_params_track_fp32_master(self):
        mesh = _mesh(4)
        step, state = make_zero_train_step(
            _loss_of, _mlp_params(dtype=jnp.bfloat16), Adam(1e-2), mesh,
            zero_stage=2)
        assert state["master"], "half params must enable master weights"
        x, y = _batch()
        for _ in range(3):
            state, loss = step(state, np.float32(1e-2), x, y)
        for k, m in state["master"].items():
            assert m.dtype == jnp.float32
            np.testing.assert_array_equal(
                np.asarray(state["params"][k]),
                np.asarray(m.astype(jnp.bfloat16)))
        assert np.isfinite(float(loss))


@needs8
class TestShardedInit:
    """make_sharded_gpt_train_step: params initialize DIRECTLY sharded on
    the mesh (no host-side full-size copy — the 6.7B enabler)."""

    def test_shards_and_trains(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed import fleet
        from paddle_tpu.models.gpt import (GPTConfig,
                                           make_sharded_gpt_train_step)
        from paddle_tpu.optimizer import AdamW

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 1, "sharding_degree": 8}
        fleet.fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.fleet.get_hybrid_communicate_group()

        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=4,
                        num_attention_heads=4, max_position_embeddings=64,
                        compute_dtype="float32")
        step, state = make_sharded_gpt_train_step(cfg, AdamW(1e-3), hcg,
                                                  zero_stage=3)
        w = state["params"]["blocks_fc1_w"]
        full = int(np.prod(w.shape))
        assert int(np.prod(w.addressable_shards[0].data.shape)) == full // 8
        m1 = state["opt"]["slots"]["blocks_fc1_w"]["moment1"]
        assert int(np.prod(m1.addressable_shards[0].data.shape)) == full // 8

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randint(0, 512, (8, 32)))
        losses = []
        for i in range(5):
            state, loss = step(state, np.float32(1e-3), jax.random.key(i),
                               x, x)
            losses.append(float(np.asarray(loss)))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0], losses

    def test_bert_and_ernie_sharded_init(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed import fleet
        from paddle_tpu.models.bert import (BertConfig,
                                            make_sharded_bert_train_step)
        from paddle_tpu.models.ernie_moe import (
            ErnieMoeConfig, make_sharded_ernie_moe_train_step)
        from paddle_tpu.optimizer import AdamW

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 1, "sharding_degree": 8}
        fleet.fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.fleet.get_hybrid_communicate_group()
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, 512, (8, 32)))

        cfg = BertConfig(vocab_size=512, hidden_size=64, num_hidden_layers=2,
                         num_attention_heads=4, max_position_embeddings=64,
                         compute_dtype="float32")
        step, state = make_sharded_bert_train_step(cfg, AdamW(1e-3), hcg,
                                                   zero_stage=3)
        w = state["params"]["blocks_fc1_w"]
        assert int(np.prod(w.addressable_shards[0].data.shape)) \
            == int(np.prod(w.shape)) // 8
        nsp = jnp.asarray(rng.randint(0, 2, (8,)))
        state, loss = step(state, np.float32(1e-3), ids, ids, nsp)
        assert np.isfinite(float(np.asarray(loss)))

        ecfg = ErnieMoeConfig(vocab_size=512, hidden_size=64, num_layers=2,
                              num_attention_heads=4, num_experts=4,
                              max_position_embeddings=64,
                              compute_dtype="float32")
        estep, estate = make_sharded_ernie_moe_train_step(
            ecfg, AdamW(1e-3), hcg, zero_stage=3)
        estate, eloss = estep(estate, np.float32(1e-3), ids, ids)
        assert np.isfinite(float(np.asarray(eloss)))


class TestOffload:
    """sharding_configs offload=True: optimizer state in host memory, update
    on the host backend (≙ reference DygraphShardingOptimizer offload)."""

    @needs8
    def test_loss_and_param_parity_vs_on_device(self):
        x, y = _batch()
        mesh = _mesh(4)
        step_d, state_d = make_zero_train_step(
            _loss_of, _mlp_params(), Adam(1e-2), mesh, zero_stage=1)
        step_h, state_h = make_zero_train_step(
            _loss_of, _mlp_params(), Adam(1e-2), mesh, zero_stage=1,
            offload=True)
        for i in range(3):
            state_d, loss_d = step_d(state_d, np.float32(1e-2), x, y)
            state_h, loss_h = step_h(state_h, np.float32(1e-2), x, y)
            np.testing.assert_allclose(float(loss_d), float(loss_h),
                                       rtol=1e-5, atol=1e-6, err_msg=f"step {i}")
        for k in state_d["params"]:
            np.testing.assert_allclose(np.asarray(state_d["params"][k]),
                                       np.asarray(state_h["params"][k]),
                                       rtol=2e-5, atol=2e-6, err_msg=k)

    @needs8
    def test_optimizer_state_lives_on_host(self):
        mesh = _mesh(4)
        step, state = make_zero_train_step(
            _loss_of, _mlp_params(dtype=jnp.bfloat16), Adam(1e-2), mesh,
            zero_stage=1, offload=True)
        cpu0 = jax.devices("cpu")[0]
        for leaf in jax.tree_util.tree_leaves(state["opt"]["slots"]):
            assert leaf.devices() == {cpu0}, leaf.devices()
        for leaf in jax.tree_util.tree_leaves(state["master"]):
            assert leaf.devices() == {cpu0}
        # params stay on the mesh (half dtype → fp32 masters exist)
        assert state["master"], "bf16 params must have host masters"
        x, y = _batch()
        state, loss = step(state, np.float32(1e-2), x, y)
        assert np.isfinite(float(loss))
        for leaf in jax.tree_util.tree_leaves(state["opt"]["slots"]):
            assert leaf.devices() == {cpu0}  # stays host-resident post-step

    @needs8
    def test_found_inf_skips_update(self):
        mesh = _mesh(2)
        step, state = make_zero_train_step(
            _loss_of, _mlp_params(), Adam(1e-2), mesh, zero_stage=1,
            offload=True)
        before = {k: np.asarray(v) for k, v in state["params"].items()}
        x, y = _batch()
        bad = x.at[0, 0].set(jnp.inf)      # inf input -> non-finite grads
        state, _ = step(state, np.float32(1e-2), bad, y)
        for k, v in state["params"].items():
            np.testing.assert_array_equal(np.asarray(v), before[k], err_msg=k)
