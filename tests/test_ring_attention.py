"""Sequence/context-parallel attention tests on the virtual CPU mesh.

The reference has no SP/CP (SURVEY.md §2.4); correctness oracle is the
full-sequence dense attention on one device (OpTest-style numpy comparison).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map  # jax>=0.8
except ImportError:
    from jax.experimental.shard_map import shard_map

from paddle_tpu.core.device import local_devices
from paddle_tpu.ops.attention import dense_attention
from paddle_tpu.ops.ring_attention import (ring_attention, ulysses_attention,
                                           sequence_parallel_attention)

needs4 = pytest.mark.skipif(len(local_devices()) < 4, reason="needs 4 devices")

B, L, H, D = 2, 32, 4, 8
SP = 4


def _mesh():
    return Mesh(np.array(local_devices()[:SP]), ("sep",))


def _qkv(seed=0):
    r = np.random.RandomState(seed)
    return [jnp.asarray(r.randn(B, L, H, D), jnp.float32) for _ in range(3)]


def _run_sharded(fn, q, k, v):
    mesh = _mesh()
    sharded = shard_map(fn, mesh=mesh,
                        in_specs=(P(None, "sep"), P(None, "sep"), P(None, "sep")),
                        out_specs=P(None, "sep"))
    return jax.jit(sharded)(q, k, v)


@needs4
@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(causal):
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, causal=causal)
    out = _run_sharded(
        lambda a, b, c: ring_attention(a, b, c, "sep", causal=causal), q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@needs4
@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(causal):
    q, k, v = _qkv(1)
    ref = dense_attention(q, k, v, causal=causal)
    out = _run_sharded(
        lambda a, b, c: ulysses_attention(
            a, b, c, "sep", causal=causal,
            attention_fn=lambda x, y, z: dense_attention(x, y, z, causal=causal)),
        q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@needs4
def test_ring_backward_matches_dense():
    q, k, v = _qkv(2)

    def loss_ref(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        mesh = _mesh()
        f = shard_map(lambda a, b, c: ring_attention(a, b, c, "sep", causal=True),
                      mesh=mesh,
                      in_specs=(P(None, "sep"),) * 3, out_specs=P(None, "sep"))
        return jnp.sum(f(q, k, v) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


@needs4
def test_dispatch_modes():
    q, k, v = _qkv(3)
    ref = dense_attention(q, k, v, causal=False)
    for mode in ("ring", "ulysses"):
        out = _run_sharded(
            lambda a, b, c, m=mode: sequence_parallel_attention(
                a, b, c, "sep", mode=m), q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


@needs4
def test_gpt_train_step_with_sep_ring_loss_parity():
    """End-to-end: GPT train step on a sep=4 mesh with ring attention matches
    the serial run (reference methodology: test_dist_base.py:1457 loss parity)."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models.gpt import GPTConfig, GPTModel, make_gpt_train_step
    from paddle_tpu.optimizer import AdamW

    losses = {}
    for sep in (1, 4):
        paddle.seed(0)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                                   "sep_degree": sep}
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=32,
                        compute_dtype="float32",
                        sequence_parallel="ring" if sep > 1 else None)
        model = GPTModel(cfg)
        opt = AdamW(1e-3)
        step, state = make_gpt_train_step(model, opt, hcg, remat=False)
        r = np.random.RandomState(0)
        x = jnp.asarray(r.randint(0, 128, (2, 32)))
        y = jnp.asarray(r.randint(0, 128, (2, 32)))
        for i in range(3):
            state, loss = step(state, jax.random.key(i), np.float32(1e-3), x, y)
        losses[sep] = float(np.asarray(loss))
    assert abs(losses[1] - losses[4]) < 1e-4, losses


needs8 = pytest.mark.skipif(len(local_devices()) < 8,
                            reason="needs 8 devices")


@needs8
@pytest.mark.parametrize("L", [2048, 32768])
def test_ring_memory_stays_per_shard_linear(L):
    """Long-context CPU-side proof (VERDICT r3 #8; the 32k case is r3's
    'add the L=32k memory assertion'): under sep=8 ring attention, the grad
    jaxpr — INCLUDING the shard_map body and cond branches — holds nothing
    bigger than the per-device (Lc,Lc) score panel / (Lc,H,D) shards, and —
    the stacking check — NO buffer anywhere carries a leading (sp-1)/sp
    stack of k/v shards.  Plain JAX AD of the fwd scan produces exactly
    that ((sp-1, B, Lc, H, D) stacked ppermute payloads = the full global
    K/V resident on every device); the hand-written ring backward re-rotates
    blocks instead.  At L=2048 the size bound alone rejects stacking; at
    L=32768 the transient score panel legitimately dominates (Lc > 7*D), so
    the shape-aware stacking check is what carries the assertion.
    Trace-only (make_jaxpr): nothing executes, so 32k costs tracing time,
    not memory."""
    H, D, sep = 4, 256, 8
    Lc = L // sep
    mesh = Mesh(np.array(jax.devices()[:sep]), ("sep",))
    q = jax.ShapeDtypeStruct((1, L, H, D), jnp.float32)

    def loss(q, k, v):
        f = shard_map(lambda a, b, c: ring_attention(a, b, c, "sep",
                                                     causal=True),
                      mesh=mesh, in_specs=(P(None, "sep"),) * 3,
                      out_specs=P(None, "sep"))
        return jnp.sum(f(q, k, v))

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, q, q)
    outer_limit = 2 * L * H * D          # global shards/grads
    panel = Lc * Lc * H                  # per-device score panel (B=1)
    shard = Lc * H * D
    inner_limit = 4 * max(panel, shard)
    # the stacking signature has the k/v-shard element count with an extra
    # leading (sp-1) or sp axis — reject it by SHAPE so it is caught even
    # when the score panel legitimately exceeds (sp-1)*shard in size
    stacked_sizes = {(sep - 1) * shard, sep * shard}  # B=1

    def is_kv_stack(shape):
        return (len(shape) >= 5 and shape[0] in (sep - 1, sep)
                and int(np.prod(shape)) in stacked_sizes)

    if L == 2048:  # shard dominates: size bound alone must catch stacking
        assert (sep - 1) * shard > inner_limit

    visited = {"inner": 0}

    def sub_jaxprs(eqn):
        for val in eqn.params.values():
            for cand in (val if isinstance(val, (tuple, list)) else [val]):
                if hasattr(cand, "jaxpr"):      # ClosedJaxpr
                    yield cand.jaxpr
                elif hasattr(cand, "eqns"):     # plain Jaxpr (shard_map)
                    yield cand

    def walk(jx, inner):
        for eqn in jx.eqns:
            is_manual = inner or eqn.primitive.name == "shard_map"
            for var in eqn.outvars:
                shape = var.aval.shape
                sz = int(np.prod(shape)) if shape else 1
                if inner:
                    visited["inner"] += 1
                    assert sz <= inner_limit, (
                        f"per-device buffer {shape} "
                        f"({eqn.primitive}) exceeds O(L/sp) bound")
                    assert not is_kv_stack(shape), (
                        f"stacked k/v shards {shape} ({eqn.primitive}) — "
                        f"the naive-AD blow-up the ring backward exists "
                        f"to avoid")
                else:
                    assert sz <= outer_limit, (
                        f"global buffer {shape} ({eqn.primitive})")
            for sub in sub_jaxprs(eqn):
                walk(sub, is_manual)

    walk(jaxpr.jaxpr, False)
    # the walker must actually have seen the ring internals — a vacuous
    # walk (e.g. shard_map body not entered) would pass every assert
    assert visited["inner"] > 20, visited

    # negative control: plain JAX AD through the fwd scan (custom_vjp
    # bypassed) DOES stack the received k/v blocks, and the same walker
    # must catch it — otherwise the checks above prove nothing
    from paddle_tpu.ops import ring_attention as R

    def naive_loss(q, k, v):
        f = shard_map(
            lambda a, b, c: R._ring_fwd_pass(
                a, b, c, "sep", True, 1.0 / np.sqrt(D))[0],
            mesh=mesh, in_specs=(P(None, "sep"),) * 3,
            out_specs=P(None, None, "sep", None))  # fwd emits (B,H,Lc,D)
        return jnp.sum(f(q, k, v))

    njaxpr = jax.make_jaxpr(jax.grad(naive_loss, argnums=(0, 1, 2)))(q, q, q)
    # outer walk: the hoisted scan residuals already violate the global
    # bound (shape (sep*(sep-1), B, Lc, H, D) on the shard_map eqn)
    with pytest.raises(AssertionError, match="global buffer"):
        walk(njaxpr.jaxpr, False)
    # and the INNER stacking detector must fire on the shard_map body
    # itself — this is the only guard at 32k, where (sep-1)*shard fits
    # under the panel-dominated size limit, so it must be shown live
    bodies = [sub for eqn in njaxpr.jaxpr.eqns
              if eqn.primitive.name == "shard_map"
              for sub in sub_jaxprs(eqn)]
    assert bodies
    fired = 0
    for body in bodies:
        try:
            walk(body, True)
        except AssertionError as e:
            assert "stacked k/v" in str(e) or "O(L/sp)" in str(e), e
            fired += 1
    assert fired, "no shard_map body tripped the stacking detector"


@needs8
def test_gpt_dp_x_sep_x_sharding_parity():
    """3-axis hybrid no other test covers: dp2 x sep2(ring) x sharding2
    (ZeRO-3) on one mesh matches the serial run (loss-parity oracle,
    ≙ reference test_dist_base.py:1457)."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models.gpt import GPTConfig, GPTModel, make_gpt_train_step
    from paddle_tpu.optimizer import AdamW

    losses = {}
    for tag, cfgs, zs in [("serial", {"dp_degree": 1}, 0),
                          ("hybrid", {"dp_degree": 2, "sep_degree": 2,
                                      "sharding_degree": 2}, 3)]:
        paddle.seed(0)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"mp_degree": 1, "pp_degree": 1, **cfgs}
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=32,
                        compute_dtype="float32",
                        sequence_parallel="ring" if "sep_degree" in cfgs
                        else None)
        model = GPTModel(cfg)
        step, state = make_gpt_train_step(model, AdamW(1e-3), hcg,
                                          remat=False, zero_stage=zs)
        r = np.random.RandomState(0)
        x = jnp.asarray(r.randint(0, 128, (4, 32)))
        y = jnp.asarray(r.randint(0, 128, (4, 32)))
        for i in range(3):
            state, loss = step(state, jax.random.key(i), np.float32(1e-3),
                               x, y)
        losses[tag] = float(np.asarray(loss))
    assert abs(losses["serial"] - losses["hybrid"]) < 1e-4, losses
