"""Sequence/context-parallel attention tests on the virtual CPU mesh.

The reference has no SP/CP (SURVEY.md §2.4); correctness oracle is the
full-sequence dense attention on one device (OpTest-style numpy comparison).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map  # jax>=0.8
except ImportError:
    from jax.experimental.shard_map import shard_map

from paddle_tpu.core.device import local_devices
from paddle_tpu.ops.attention import dense_attention
from paddle_tpu.ops.ring_attention import (ring_attention, ulysses_attention,
                                           sequence_parallel_attention)

needs4 = pytest.mark.skipif(len(local_devices()) < 4, reason="needs 4 devices")

B, L, H, D = 2, 32, 4, 8
SP = 4


def _mesh():
    return Mesh(np.array(local_devices()[:SP]), ("sep",))


def _qkv(seed=0):
    r = np.random.RandomState(seed)
    return [jnp.asarray(r.randn(B, L, H, D), jnp.float32) for _ in range(3)]


def _run_sharded(fn, q, k, v):
    mesh = _mesh()
    sharded = shard_map(fn, mesh=mesh,
                        in_specs=(P(None, "sep"), P(None, "sep"), P(None, "sep")),
                        out_specs=P(None, "sep"))
    return jax.jit(sharded)(q, k, v)


@needs4
@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(causal):
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, causal=causal)
    out = _run_sharded(
        lambda a, b, c: ring_attention(a, b, c, "sep", causal=causal), q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@needs4
@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(causal):
    q, k, v = _qkv(1)
    ref = dense_attention(q, k, v, causal=causal)
    out = _run_sharded(
        lambda a, b, c: ulysses_attention(
            a, b, c, "sep", causal=causal,
            attention_fn=lambda x, y, z: dense_attention(x, y, z, causal=causal)),
        q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@needs4
def test_ring_backward_matches_dense():
    q, k, v = _qkv(2)

    def loss_ref(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        mesh = _mesh()
        f = shard_map(lambda a, b, c: ring_attention(a, b, c, "sep", causal=True),
                      mesh=mesh,
                      in_specs=(P(None, "sep"),) * 3, out_specs=P(None, "sep"))
        return jnp.sum(f(q, k, v) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


@needs4
def test_dispatch_modes():
    q, k, v = _qkv(3)
    ref = dense_attention(q, k, v, causal=False)
    for mode in ("ring", "ulysses"):
        out = _run_sharded(
            lambda a, b, c, m=mode: sequence_parallel_attention(
                a, b, c, "sep", mode=m), q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


@needs4
def test_gpt_train_step_with_sep_ring_loss_parity():
    """End-to-end: GPT train step on a sep=4 mesh with ring attention matches
    the serial run (reference methodology: test_dist_base.py:1457 loss parity)."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models.gpt import GPTConfig, GPTModel, make_gpt_train_step
    from paddle_tpu.optimizer import AdamW

    losses = {}
    for sep in (1, 4):
        paddle.seed(0)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                                   "sep_degree": sep}
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=32,
                        compute_dtype="float32",
                        sequence_parallel="ring" if sep > 1 else None)
        model = GPTModel(cfg)
        opt = AdamW(1e-3)
        step, state = make_gpt_train_step(model, opt, hcg, remat=False)
        r = np.random.RandomState(0)
        x = jnp.asarray(r.randint(0, 128, (2, 32)))
        y = jnp.asarray(r.randint(0, 128, (2, 32)))
        for i in range(3):
            state, loss = step(state, jax.random.key(i), np.float32(1e-3), x, y)
        losses[sep] = float(np.asarray(loss))
    assert abs(losses[1] - losses[4]) < 1e-4, losses


needs8 = pytest.mark.skipif(len(local_devices()) < 8,
                            reason="needs 8 devices")


@needs8
def test_ring_memory_stays_per_shard_linear():
    """Long-context CPU-side proof (VERDICT r3 #8): under sep=8 ring
    attention, the grad jaxpr — INCLUDING the shard_map body and cond
    branches — holds nothing bigger than a few per-device panels/shards.
    Plain JAX AD of the fwd scan stacks (sp-1) received k/v shards
    ((sp-1)*Lc*H*D per device = the full global K/V), which this bound
    rejects; dims are chosen so that blow-up exceeds the limit while the
    legitimate (B,H,Lc,Lc) score panel and (Lc,H,D) shards fit."""
    L, H, D, sep = 2048, 4, 256, 8
    Lc = L // sep
    mesh = Mesh(np.array(jax.devices()[:sep]), ("sep",))
    q = jax.ShapeDtypeStruct((1, L, H, D), jnp.float32)

    def loss(q, k, v):
        f = shard_map(lambda a, b, c: ring_attention(a, b, c, "sep",
                                                     causal=True),
                      mesh=mesh, in_specs=(P(None, "sep"),) * 3,
                      out_specs=P(None, "sep"))
        return jnp.sum(f(q, k, v))

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, q, q)
    outer_limit = 2 * L * H * D          # global shards/grads
    panel = Lc * Lc * H                  # per-device score panel (B=1)
    shard = Lc * H * D
    inner_limit = 4 * max(panel, shard)  # << (sep-1)*shard = 7*shard
    assert (sep - 1) * shard > inner_limit  # the guarded blow-up must trip

    visited = {"inner": 0}

    def sub_jaxprs(eqn):
        for val in eqn.params.values():
            for cand in (val if isinstance(val, (tuple, list)) else [val]):
                if hasattr(cand, "jaxpr"):      # ClosedJaxpr
                    yield cand.jaxpr
                elif hasattr(cand, "eqns"):     # plain Jaxpr (shard_map)
                    yield cand

    def walk(jx, inner):
        for eqn in jx.eqns:
            is_manual = inner or eqn.primitive.name == "shard_map"
            for var in eqn.outvars:
                sz = int(np.prod(var.aval.shape)) if var.aval.shape else 1
                if inner:
                    visited["inner"] += 1
                    assert sz <= inner_limit, (
                        f"per-device buffer {var.aval.shape} "
                        f"({eqn.primitive}) exceeds O(L/sp) bound")
                else:
                    assert sz <= outer_limit, (
                        f"global buffer {var.aval.shape} ({eqn.primitive})")
            for sub in sub_jaxprs(eqn):
                walk(sub, is_manual)

    walk(jaxpr.jaxpr, False)
    # the walker must actually have seen the ring internals — a vacuous
    # walk (e.g. shard_map body not entered) would pass every assert
    assert visited["inner"] > 20, visited


@needs8
def test_gpt_dp_x_sep_x_sharding_parity():
    """3-axis hybrid no other test covers: dp2 x sep2(ring) x sharding2
    (ZeRO-3) on one mesh matches the serial run (loss-parity oracle,
    ≙ reference test_dist_base.py:1457)."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models.gpt import GPTConfig, GPTModel, make_gpt_train_step
    from paddle_tpu.optimizer import AdamW

    losses = {}
    for tag, cfgs, zs in [("serial", {"dp_degree": 1}, 0),
                          ("hybrid", {"dp_degree": 2, "sep_degree": 2,
                                      "sharding_degree": 2}, 3)]:
        paddle.seed(0)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"mp_degree": 1, "pp_degree": 1, **cfgs}
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=32,
                        compute_dtype="float32",
                        sequence_parallel="ring" if "sep_degree" in cfgs
                        else None)
        model = GPTModel(cfg)
        step, state = make_gpt_train_step(model, AdamW(1e-3), hcg,
                                          remat=False, zero_stage=zs)
        r = np.random.RandomState(0)
        x = jnp.asarray(r.randint(0, 128, (4, 32)))
        y = jnp.asarray(r.randint(0, 128, (4, 32)))
        for i in range(3):
            state, loss = step(state, jax.random.key(i), np.float32(1e-3),
                               x, y)
        losses[tag] = float(np.asarray(loss))
    assert abs(losses["serial"] - losses["hybrid"]) < 1e-4, losses
