"""tpulint CI gate, exercised in-suite so the tier-1 run enforces it.

Three layers: (1) the committed tree is exactly at the committed baseline
(no new violations, no stale entries — the ratchet is tight in both
directions) and the sweep fits the <20 s CPU budget; (2) the CLI's
documented exit-code contract (0 clean / 1 new / 2 usage / 3 stale)
round-trips on a scratch tree, including injection of a fixture violation
naming the rule and file:line; (3) the JSON output schema is frozen."""

import json
import pathlib
import subprocess
import sys
import time

from paddle_tpu.analysis import (PROGRAM_RULES, RULES, SCHEMA_VERSION,
                                 analyze_program, diff_baseline, lint_paths,
                                 load_baseline, render_json)

ROOT = pathlib.Path(__file__).parent.parent
CLI = ROOT / "tools" / "tpulint.py"
BASELINE = ROOT / "tools" / "tpulint_baseline.json"
FIXTURES = ROOT / "paddle_tpu" / "analysis" / "fixtures"


def _run(*args, **kw):
    return subprocess.run([sys.executable, str(CLI), *map(str, args)],
                          capture_output=True, text=True, **kw)


# ------------------------------------------------------------ committed tree

def test_tree_is_clean_against_committed_baseline_under_budget():
    # Timing-based half: retry once so a loaded/cpu-shares-throttled CI
    # host can't flake the budget check (same tolerance pattern as
    # test_dataloader_mp); the correctness half never retries.  Both
    # stages run — the committed baseline carries per-file AND program
    # counts, so a per-file-only diff would misread the program entries
    # as stale.  Budgets: per-file < 20 s, whole sweep < 30 s.
    paths = [ROOT / "paddle_tpu", ROOT / "tools"]
    for _attempt in range(2):
        t0 = time.monotonic()
        findings = lint_paths(paths, root=ROOT)
        per_file_elapsed = time.monotonic() - t0
        program_findings, _report = analyze_program(paths, root=ROOT)
        elapsed = time.monotonic() - t0
        if elapsed < 30.0:
            break
    new, stale = diff_baseline(findings + program_findings,
                               load_baseline(BASELINE))
    assert not new, ("NEW tpulint violations (fix them or, for a pre-existing "
                     "class, rebaseline deliberately):\n"
                     + "\n".join(f.render() for f in new))
    assert not stale, (f"STALE baseline entries (violations were burned down "
                       f"— shrink the ratchet with --write-baseline "
                       f"--program): {stale}")
    assert per_file_elapsed < 20.0, (f"per-file sweep took "
                                     f"{per_file_elapsed:.1f}s, budget is 20s")
    assert elapsed < 30.0, (f"full sweep (files + program) took "
                            f"{elapsed:.1f}s, budget is 30s")


def test_every_rule_has_a_baselined_true_positive():
    """'No speculative rules': each registered rule must have at least one
    recorded site in the committed baseline (live tree or frozen fixture
    corpus) — a rule with zero recorded positives is either untested or
    dead weight, and this test forces that conversation."""
    counts = load_baseline(BASELINE)
    seen = {rule for per_file in counts.values() for rule in per_file}
    missing = sorted((set(RULES) | set(PROGRAM_RULES)) - seen)
    assert not missing, (f"rules with no baselined true-positive: {missing} "
                         f"— add a fixture under paddle_tpu/analysis/fixtures/ "
                         f"and rebaseline")


def test_cli_gate_exits_zero_on_committed_tree():
    res = _run("paddle_tpu", "tools", cwd=ROOT)
    assert res.returncode == 0, res.stdout + res.stderr


def test_cli_gate_exits_zero_on_committed_tree_with_program():
    res = _run("--program", "paddle_tpu", "tools", cwd=ROOT)
    assert res.returncode == 0, res.stdout + res.stderr


def test_injected_violation_fails_naming_rule_and_location(tmp_path):
    """Acceptance: injecting any single fixture violation must turn the
    gate non-zero and name the rule and file:line.  Injection = linting one
    extra file that is not in the baseline; the repo itself stays clean."""
    injected = tmp_path / "injected_regression.py"
    injected.write_text((FIXTURES / "bad_silent_except.py").read_text())
    res = _run("paddle_tpu", "tools", injected, cwd=ROOT)
    assert res.returncode == 1, res.stdout + res.stderr
    assert "silent-except" in res.stdout
    assert "injected_regression.py:8:" in res.stdout  # file:line of site 1


# ------------------------------------------------------- ratchet round-trip

def test_exit_code_contract_round_trip(tmp_path):
    proj = tmp_path / "proj"
    proj.mkdir()
    baseline = tmp_path / "baseline.json"
    bad = FIXTURES / "bad_silent_except.py"
    (proj / "a.py").write_text(bad.read_text())

    def run(*extra):
        return _run("--root", tmp_path, "--baseline", baseline, "proj", *extra)

    # no baseline file yet → usage error, distinct from lint failure
    assert run().returncode == 2
    # freeze the pre-existing violations → gate goes green
    assert run("--write-baseline").returncode == 0
    assert run().returncode == 0
    # a NEW violation (count above baseline) → exit 1, rule + file:line named
    (proj / "b.py").write_text("try:\n    pass\nexcept Exception:\n    pass\n")
    res = run()
    assert res.returncode == 1
    assert "silent-except" in res.stdout and "proj/b.py:3:" in res.stdout
    # burn a violation down → STALE baseline, exit 3 (ratchet must shrink)
    (proj / "b.py").unlink()
    (proj / "a.py").write_text("x = 1\n")
    res = run()
    assert res.returncode == 3
    assert "STALE" in res.stderr
    # shrinking the ratchet restores green
    assert run("--write-baseline").returncode == 0
    assert run().returncode == 0
    assert json.loads(baseline.read_text())["counts"] == {}


def test_overlapping_paths_do_not_double_count():
    """paddle_tpu twice (or a dir plus its subdir) must not double every
    fixture count and falsely trip the ratchet."""
    res = _run("paddle_tpu", "paddle_tpu", "paddle_tpu/analysis", "tools",
               cwd=ROOT)
    assert res.returncode == 0, res.stdout + res.stderr


def test_write_baseline_refuses_path_subset(tmp_path):
    proj = tmp_path / "proj"
    (proj / "sub").mkdir(parents=True)
    (proj / "a.py").write_text("try:\n    pass\nexcept Exception:\n    pass\n")
    (proj / "sub" / "b.py").write_text("x = 1\n")
    baseline = tmp_path / "baseline.json"
    full = _run("--root", tmp_path, "--baseline", baseline, "proj",
                "--write-baseline")
    assert full.returncode == 0
    subset = _run("--root", tmp_path, "--baseline", baseline, "proj/sub",
                  "--write-baseline")
    assert subset.returncode == 2
    assert "refusing" in subset.stderr
    # the committed counts survived the refused overwrite
    assert json.loads(baseline.read_text())["counts"]


def test_no_baseline_mode_reports_everything(tmp_path):
    src = tmp_path / "x.py"
    src.write_text("try:\n    pass\nexcept Exception:\n    pass\n")
    assert _run("--no-baseline", src).returncode == 1
    src.write_text("x = 1\n")
    assert _run("--no-baseline", src).returncode == 0


# ------------------------------------------------------------------- output

def test_json_output_schema():
    findings = lint_paths([FIXTURES / "bad_no_print.py"], root=ROOT)
    doc = json.loads(render_json(findings))
    assert doc["version"] == SCHEMA_VERSION
    assert isinstance(doc["findings"], list) and doc["findings"]
    for f in doc["findings"]:
        assert set(f) == {"path", "line", "col", "rule", "message"}
        assert isinstance(f["line"], int) and f["line"] >= 1
        assert isinstance(f["col"], int) and f["col"] >= 1
        assert f["rule"] in set(RULES) | {"bad-pragma", "syntax-error"}
    path = doc["findings"][0]["path"]
    assert doc["counts"][path]["no-print"] == 1


def test_cli_json_flag_emits_parseable_json(tmp_path):
    src = tmp_path / "x.py"
    src.write_text("try:\n    pass\nexcept Exception:\n    pass\n")
    res = _run("--no-baseline", "--json", src)
    assert res.returncode == 1
    doc = json.loads(res.stdout)
    assert doc["version"] == SCHEMA_VERSION
    assert [f["rule"] for f in doc["findings"]] == ["silent-except"]


def test_list_rules_catalog():
    res = _run("--list-rules")
    assert res.returncode == 0
    for rule in RULES:
        assert rule in res.stdout


def test_collect_smoke_has_tpulint_stage():
    """The standalone gate must run the linter WITH the whole-program
    passes; keep the wiring honest."""
    script = (ROOT / "tools" / "collect_smoke.sh").read_text()
    assert "tpulint.py --program paddle_tpu tools" in script
