"""MoE / expert-parallel tests (reference capability: global_scatter/gather
distributed/utils.py:57,179 + downstream gate layers; oracle = numpy routing
and single-device equivalence, OpTest-style)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.core.device import local_devices
from paddle_tpu.ops.moe import topk_gating, moe_dispatch, moe_combine, moe_ffn

try:
    from jax import shard_map  # jax>=0.8
except ImportError:
    from jax.experimental.shard_map import shard_map

needs4 = pytest.mark.skipif(len(local_devices()) < 4, reason="needs 4 devices")


def test_topk_gating_invariants():
    r = np.random.RandomState(0)
    T, E, k = 64, 8, 2
    logits = jnp.asarray(r.randn(T, E), jnp.float32)
    combine, dispatch, aux = topk_gating(logits, k=k)
    C = combine.shape[-1]
    d = np.asarray(dispatch)
    # each token goes to at most k expert slots, each slot holds ≤1 token
    assert d.sum(axis=(1, 2)).max() <= k
    assert d.sum(axis=0).max() <= 1
    # combine weights sit exactly on dispatched slots with softmax gate probs
    c = np.asarray(combine)
    assert (c[~d] == 0).all() and (c[d] > 0).all()
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_top1_routing_matches_numpy_oracle():
    r = np.random.RandomState(1)
    T, E, H = 16, 4, 8
    x = jnp.asarray(r.randn(T, H), jnp.float32)
    logits = jnp.asarray(r.randn(T, E), jnp.float32)
    combine, dispatch, _ = topk_gating(logits, k=1, capacity=T)  # no drops
    out = moe_combine(moe_dispatch(x, dispatch), combine, dtype=jnp.float32)
    # oracle: each token scaled by its top-1 softmax prob (identity experts)
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    top1 = probs.argmax(-1)
    want = np.asarray(x) * probs[np.arange(T), top1][:, None]
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-6)


def test_moe_layer_trains():
    paddle.seed(0)
    layer = nn.MoELayer(d_model=16, d_hidden=32, num_experts=4, top_k=2)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=layer.parameters())
    r = np.random.RandomState(0)
    x = paddle.to_tensor(r.randn(8, 4, 16).astype("float32"))
    target = paddle.to_tensor(r.randn(8, 4, 16).astype("float32"))
    first = None
    for _ in range(20):
        out = layer(x)
        loss = ((out - target) ** 2).mean() + 0.01 * layer.aux_loss
        loss.backward()
        opt.step(); opt.clear_grad()
        if first is None:
            first = float(loss)
    assert float(loss) < first


@needs4
def test_expert_parallel_matches_single_device():
    r = np.random.RandomState(2)
    T, E, H, I = 32, 4, 8, 16
    x = jnp.asarray(r.randn(T, H), jnp.float32)
    gw = jnp.asarray(r.randn(H, E), jnp.float32)
    w1 = jnp.asarray(0.1 * r.randn(E, H, I), jnp.float32)
    b1 = jnp.zeros((E, I), jnp.float32)
    w2 = jnp.asarray(0.1 * r.randn(E, I, H), jnp.float32)
    b2 = jnp.zeros((E, H), jnp.float32)

    ref, aux_ref = moe_ffn(x, gw, w1, b1, w2, b2, k=2)
    mesh = Mesh(np.array(local_devices()[:4]), ("data",))
    f = jax.jit(lambda *a: moe_ffn(*a, k=2, mesh=mesh, expert_axis="data"))
    out, aux = f(x, gw, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    assert abs(float(aux) - float(aux_ref)) < 1e-5


@needs4
def test_global_scatter_gather_roundtrip():
    from paddle_tpu.distributed.utils import global_scatter, global_gather
    mesh = Mesh(np.array(local_devices()[:4]), ("data",))
    r = np.random.RandomState(3)
    # 4 ranks × (world=4 × n_expert=2 × capacity=3) rows × H=5
    x = jnp.asarray(r.randn(4 * 24, 5), jnp.float32)

    def roundtrip(xl):
        return global_gather(global_scatter(xl, group="data"), group="data")

    f = shard_map(roundtrip, mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    np.testing.assert_allclose(np.asarray(jax.jit(f)(x)), np.asarray(x))

    # scatter semantics: rank r's block w lands on rank w at block r
    def scatter_only(xl):
        return global_scatter(xl, group="data")

    g = shard_map(scatter_only, mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    out = np.asarray(jax.jit(g)(x)).reshape(4, 4, 6, 5)  # (rank, block, rows, H)
    xin = np.asarray(x).reshape(4, 4, 6, 5)
    for rk in range(4):
        for w in range(4):
            np.testing.assert_allclose(out[rk, w], xin[w, rk])


# ---------------------------------------------------------------------------
# Ragged exchange (VERDICT round-1 #7): reference global_scatter semantics
# with per-expert counts, via pad → all_to_all → sort-compact.
# ---------------------------------------------------------------------------

def _ragged_oracle(xs, counts, W, El):
    """Numpy simulation of the reference's grouped send/recv loops
    (operators/collective/global_scatter_op.cu.cc): returns per-rank
    (received rows in expert-major order, recv_counts (W, El))."""
    outs = []
    for me in range(W):
        rows, rc = [], np.zeros((W, El), np.int64)
        for el in range(El):
            for src in range(W):
                d = me * El + el
                c = int(counts[src][d])
                off = int(np.sum(counts[src][:d]))
                rows.append(xs[src][off:off + c])
                rc[src, el] = c
        outs.append((np.concatenate(rows, axis=0) if rows else
                     np.zeros((0, xs[0].shape[1])), rc))
    return outs


@needs4
def test_ragged_global_scatter_matches_oracle():
    from paddle_tpu.distributed.utils import ragged_global_scatter
    W, El, T, H = 4, 2, 12, 5
    mesh = Mesh(np.array(local_devices()[:W]), ("data",))
    r = np.random.RandomState(7)
    xs = [r.randn(T, H).astype(np.float32) for _ in range(W)]
    # ragged counts: each rank splits its T rows over W*El destinations
    counts = []
    for _ in range(W):
        c = r.multinomial(T, np.ones(W * El) / (W * El))
        counts.append(c.astype(np.int32))
    X = jnp.asarray(np.stack(xs)).reshape(W * T, H)
    C = jnp.asarray(np.stack(counts)).reshape(W * W * El)

    def f(xl, cl):
        out, rc, _ = ragged_global_scatter(xl, cl, group="data")
        return out, rc

    g = shard_map(f, mesh=mesh, in_specs=(P("data"), P("data")),
                  out_specs=(P("data"), P("data")))
    out, rc = jax.jit(g)(X, C)
    out = np.asarray(out).reshape(W, W * T, H)
    rc = np.asarray(rc).reshape(W, W, El)
    oracle = _ragged_oracle(xs, counts, W, El)
    for me in range(W):
        ref_rows, ref_rc = oracle[me]
        n = ref_rc.sum()
        np.testing.assert_array_equal(rc[me], ref_rc, err_msg=f"rank {me} counts")
        np.testing.assert_allclose(out[me, :n], ref_rows, rtol=1e-6,
                                   err_msg=f"rank {me} rows")
        np.testing.assert_allclose(out[me, n:], 0.0)


@needs4
def test_ragged_scatter_gather_roundtrip_with_expert_transform():
    """Tokens go out ragged, each expert scales its tokens, results come back
    to the original rows — end-to-end EP compute with non-uniform routing."""
    from paddle_tpu.distributed.utils import (ragged_global_gather,
                                              ragged_global_scatter)
    W, El, T, H = 4, 2, 10, 3
    mesh = Mesh(np.array(local_devices()[:W]), ("data",))
    r = np.random.RandomState(8)
    xs = [r.randn(T, H).astype(np.float32) for _ in range(W)]
    counts = [r.multinomial(T, np.ones(W * El) / (W * El)).astype(np.int32)
              for _ in range(W)]
    X = jnp.asarray(np.stack(xs)).reshape(W * T, H)
    C = jnp.asarray(np.stack(counts)).reshape(W * W * El)

    def f(xl, cl):
        out, rc, perm = ragged_global_scatter(xl, cl, group="data")
        # expert el on rank me scales by (me*El + el + 1); rows are
        # expert-major so expert of each row follows from rc
        me = jax.lax.axis_index("data")
        per_expert = jnp.sum(rc, axis=0)              # (El,)
        cum = jnp.cumsum(per_expert)
        row = jnp.arange(out.shape[0])
        el = jnp.sum(row[:, None] >= cum[None, :], axis=1)
        el = jnp.minimum(el, El - 1)
        scale = (me * El + el + 1).astype(out.dtype)
        y = out * scale[:, None]
        back = ragged_global_gather(y, cl, perm, rows=xl.shape[0],
                                    group="data")
        return back

    g = shard_map(f, mesh=mesh, in_specs=(P("data"), P("data")),
                  out_specs=P("data"))
    back = np.asarray(jax.jit(g)(X, C)).reshape(W, T, H)
    # oracle: row destined to global expert d gets scaled by (d+1)
    for src in range(W):
        off = 0
        for d in range(W * El):
            c = int(counts[src][d])
            np.testing.assert_allclose(back[src, off:off + c],
                                       xs[src][off:off + c] * (d + 1),
                                       rtol=1e-6, err_msg=f"src {src} dest {d}")
            off += c


@needs4
def test_global_scatter_ragged_counts_raise():
    """Back-compat contract: the reference-shaped wrapper rejects ragged
    counts with a pointer to the ragged pair (round-2 review finding)."""
    from paddle_tpu.distributed.utils import global_scatter
    import pytest as _pytest
    x = jnp.ones((8, 4))
    with _pytest.raises(ValueError, match="ragged_global_scatter"):
        global_scatter(x, local_count=np.array([1, 3, 2, 2]), group="data")


@needs4
def test_ragged_scatter_small_block_raises():
    from paddle_tpu.distributed.utils import ragged_global_scatter
    import pytest as _pytest
    W, T, H = 4, 8, 3
    mesh = Mesh(np.array(local_devices()[:W]), ("data",))
    X = jnp.ones((W * T, H))
    counts = np.zeros((W, W), np.int32)
    counts[:, 0] = T  # every rank sends all rows to rank 0
    C = jnp.asarray(counts.reshape(-1))

    def f(xl, cl):
        out, rc, _ = ragged_global_scatter(xl, cl, group="data", block=4)
        return out

    g = shard_map(f, mesh=mesh, in_specs=(P("data"), P("data")),
                  out_specs=P("data"))
    with _pytest.raises(ValueError, match="block"):
        jax.jit(g)(X, C)


class TestIndexDispatchParity:
    """moe_ffn_indices must match the einsum moe_ffn bit-for-tolerance."""

    def _inputs(self, T=64, H=16, E=4, I=32, seed=0):
        import numpy as np
        rng = np.random.RandomState(seed)
        x = jnp.asarray(rng.standard_normal((T, H)).astype("float32"))
        gw = jnp.asarray(rng.standard_normal((H, E)).astype("float32") * 0.1)
        w1 = jnp.asarray(rng.standard_normal((E, H, I)).astype("float32") * 0.1)
        b1 = jnp.zeros((E, I))
        w2 = jnp.asarray(rng.standard_normal((E, I, H)).astype("float32") * 0.1)
        b2 = jnp.zeros((E, H))
        return x, gw, w1, b1, w2, b2

    @pytest.mark.parametrize("k", [1, 2])
    def test_forward_parity(self, k):
        import numpy as np
        from paddle_tpu.ops.moe import moe_ffn, moe_ffn_indices
        x, gw, w1, b1, w2, b2 = self._inputs()
        o1, a1 = moe_ffn(x, gw, w1, b1, w2, b2, k=k, capacity_factor=1.25)
        o2, a2 = moe_ffn_indices(x, gw, w1, b1, w2, b2, k=k, capacity_factor=1.25)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(a1), float(a2), rtol=1e-6)

    def test_grad_parity(self):
        import numpy as np
        from paddle_tpu.ops.moe import moe_ffn, moe_ffn_indices
        x, gw, w1, b1, w2, b2 = self._inputs(T=32, H=8, E=2, I=16, seed=1)

        def loss(fn, xx):
            out, aux = fn(xx, gw, w1, b1, w2, b2, k=2)
            return jnp.sum(out ** 2) + aux

        g1 = jax.grad(lambda xx: loss(moe_ffn, xx))(x)
        g2 = jax.grad(lambda xx: loss(moe_ffn_indices, xx))(x)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=1e-5)

    def test_overflow_drops_match(self):
        # tiny capacity forces drops on both paths identically
        import numpy as np
        from paddle_tpu.ops.moe import moe_ffn, moe_ffn_indices
        x, gw, w1, b1, w2, b2 = self._inputs(T=64, H=16, E=4, seed=2)
        o1, _ = moe_ffn(x, gw, w1, b1, w2, b2, k=2, capacity_factor=0.3)
        o2, _ = moe_ffn_indices(x, gw, w1, b1, w2, b2, k=2, capacity_factor=0.3)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                                   rtol=1e-5, atol=1e-6)


class TestGatherDispatch:
    def test_gather_equals_indices_at_nodrop(self):
        """moe_ffn_gather == moe_ffn_indices with a no-drop capacity (the
        contract the decode path relies on), for k=1 and k=2."""
        from paddle_tpu.ops.moe import moe_ffn_gather, moe_ffn_indices

        rs = np.random.RandomState(0)
        T, H, I, E = 10, 16, 32, 4
        x = jnp.asarray(rs.randn(T, H), jnp.float32)
        gw = jnp.asarray(rs.randn(H, E), jnp.float32)
        w1 = jnp.asarray(rs.randn(E, H, I) * 0.1, jnp.float32)
        b1 = jnp.asarray(rs.randn(E, I) * 0.1, jnp.float32)
        w2 = jnp.asarray(rs.randn(E, I, H) * 0.1, jnp.float32)
        b2 = jnp.asarray(rs.randn(E, H) * 0.1, jnp.float32)
        for k in (1, 2):
            want, _ = moe_ffn_indices(x, gw, w1, b1, w2, b2, k=k,
                                      capacity_factor=float(E) / k)
            got = moe_ffn_gather(x, gw, w1, b1, w2, b2, k=k)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-5, atol=2e-6, err_msg=f"k={k}")

    def test_gather_equals_indices_bf16(self):
        """The equality contract must hold at the default bf16 compute dtype
        too (decode runs bf16 in production; the combine accumulates fp32 on
        both paths)."""
        from paddle_tpu.ops.moe import moe_ffn_gather, moe_ffn_indices

        rs = np.random.RandomState(1)
        T, H, I, E = 8, 16, 32, 4
        x = jnp.asarray(rs.randn(T, H), jnp.bfloat16)
        gw = jnp.asarray(rs.randn(H, E), jnp.float32)
        w1 = jnp.asarray(rs.randn(E, H, I) * 0.1, jnp.float32)
        b1 = jnp.asarray(rs.randn(E, I) * 0.1, jnp.float32)
        w2 = jnp.asarray(rs.randn(E, I, H) * 0.1, jnp.float32)
        b2 = jnp.asarray(rs.randn(E, H) * 0.1, jnp.float32)
        want, _ = moe_ffn_indices(x, gw, w1, b1, w2, b2, k=2,
                                  capacity_factor=float(E) / 2)
        got = moe_ffn_gather(x, gw, w1, b1, w2, b2, k=2)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32),
                                   rtol=3e-2, atol=3e-2)
