"""Test harness config.

- Virtual 8-device CPU mesh (the reference's multi-GPU tests map to this —
  SURVEY.md §4: xla_force_host_platform_device_count replaces the 2-GPU gate).
- Highest matmul precision so numpy-oracle comparisons (OpTest-style) are
  meaningful; production keeps the TPU-default bf16 MXU path.
"""

import os
import sys

# TPU mode needs BOTH the env var and an explicit `-m tpu` selection; a plain
# `pytest` run with the env var exported must still get the CPU forcing (the
# tunnel-dial hang is the round-1 failure mode this guards against).
def _tpu_selected(argv):
    """True when a -m marker expression selects tpu tests (``-m tpu``,
    ``-m=tpu``, ``-m "tpu and ..."`` — but not ``-m "not tpu"``)."""
    exprs = [a.split("=", 1)[1] for a in argv if a.startswith("-m=")]
    exprs += [a for i, a in enumerate(argv)
              if i > 0 and argv[i - 1] == "-m"]
    import re
    return any(re.search(r"(^|[ (])tpu([ )]|$)", e)
               and not re.search(r"not\s+tpu", e) for e in exprs)


_TPU_RUN = (os.environ.get("PADDLE_TPU_TEST_TPU") == "1"
            and _tpu_selected(sys.argv))

if not _TPU_RUN:
    os.environ["JAX_PLATFORMS"] = "cpu"  # tests run on the virtual CPU mesh
    os.environ["PADDLE_TPU_PLATFORM"] = "cpu"  # force CPU even if a PJRT plugin hijacks the default
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

if not _TPU_RUN:
    # The TPU PJRT plugin's sitecustomize imports jax at interpreter startup
    # and force-selects its own platform, so the env var above is latched too
    # late — override the live config (legal until the first backend
    # initializes).
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu as paddle
    paddle.seed(2024)
    np.random.seed(2024)
    yield


@pytest.fixture
def lock_sanitizer():
    """Opt-in runtime lock-discipline recorder (docs/STATIC_ANALYSIS.md
    § Lock-discipline sanitizer).  Tests ``instrument()`` the objects
    under threaded exercise; any lock-order inversion or guarded-by
    violation recorded during the test fails it at teardown with every
    racing site listed."""
    from paddle_tpu.analysis import LockSanitizer
    san = LockSanitizer("pytest")
    yield san
    san.assert_clean()
