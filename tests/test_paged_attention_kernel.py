"""Pallas paged-attention decode kernel (ops/paged_attention.py): the
in-kernel block-table walk must reproduce cached_attention's kq=1
semantics over a PagedKV exactly — the gather fallback is the oracle —
including per-slot clocks, left-pad masks, trash-pointing inactive rows,
and the column-skip beyond each clock.  CPU CI runs interpret mode
(FLAGS_paged_attn_interpret); the Mosaic lowering is exercised by the
-m tpu smoke suite on hardware."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.flags import set_flags
from paddle_tpu.models._decode import PagedKV, cached_attention
from paddle_tpu.ops.paged_attention import paged_decode_attention


def _rand_case(seed, S=4, nh=4, hd=16, NB1=11, bs=8, C=4):
    rng = np.random.RandomState(seed)
    pool_k = jnp.asarray(rng.randn(NB1, bs, nh, hd), jnp.float32)
    pool_v = jnp.asarray(rng.randn(NB1, bs, nh, hd), jnp.float32)
    table = jnp.asarray(rng.randint(0, NB1, (S, C)), jnp.int32)
    t = jnp.asarray(rng.randint(0, C * bs, S), jnp.int32)
    pad = jnp.minimum(jnp.asarray(rng.randint(0, bs, S), jnp.int32), t)
    q = jnp.asarray(rng.randn(S, nh, hd), jnp.float32)
    return q, pool_k, pool_v, table, t, pad


class TestPagedKernelParity:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_matches_gather_fallback(self, seed):
        q, pk, pv, table, t, pad = _rand_case(seed)
        ref = cached_attention(q[:, None], PagedKV(pk, table),
                               PagedKV(pv, table), t, pad_lens=pad)[:, 0]
        got = paged_decode_attention(q, pk, pv, table, t, pad,
                                     interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_no_pad_and_trash_rows(self):
        """pad_lens=None; one row's table is all-trash (an inactive slot):
        its output is garbage-but-finite and other rows are unaffected."""
        q, pk, pv, table, t, pad = _rand_case(7)
        table = table.at[2].set(0)                   # row 2 -> trash
        ref = cached_attention(q[:, None], PagedKV(pk, table),
                               PagedKV(pv, table), t)[:, 0]
        got = paged_decode_attention(q, pk, pv, table, t, None,
                                     interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        assert np.isfinite(np.asarray(got)).all()

    def test_clock_zero_and_full(self):
        """Boundary clocks: t=0 (only position 0 attendable) and
        t=C*bs-1 (every table position)."""
        C, bs = 4, 8                           # _rand_case defaults
        q, pk, pv, table, t, pad = _rand_case(11, C=C, bs=bs)
        t = jnp.asarray([0, C * bs - 1, 16, 0], jnp.int32)
        pad = jnp.zeros_like(pad)
        ref = cached_attention(q[:, None], PagedKV(pk, table),
                               PagedKV(pv, table), t, pad_lens=pad)[:, 0]
        got = paged_decode_attention(q, pk, pv, table, t, pad,
                                     interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestEngineWithKernel:
    def test_engine_outputs_identical_with_kernel(self):
        """The serving engine produces token-identical outputs with the
        in-kernel table walk on vs the gather fallback, across mixed
        prompts, chunked sync, and slot reuse."""
        from paddle_tpu.models.gpt import GPTConfig, GPTModel
        from paddle_tpu.serving import PagedContinuousBatchingEngine
        paddle.seed(11)
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=96,
                        compute_dtype="float32")
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        reqs = [([5, 17, 3], 9), ([40, 2], 5), ([61], 7), ([9, 9, 1], 6)]

        def run(interp):
            set_flags({"FLAGS_paged_attn_interpret": interp})
            try:
                model.__dict__.pop("_serving_programs", None)
                eng = PagedContinuousBatchingEngine(
                    model, params, max_slots=3, max_len=32, block_size=4,
                    prompt_buckets=[8], ticks_per_sync=2)
                rids = [eng.add_request(p, n) for p, n in reqs]
                got = eng.run_to_completion(max_ticks=200)
                return [got[r] for r in rids]
            finally:
                set_flags({"FLAGS_paged_attn_interpret": False})
                model.__dict__.pop("_serving_programs", None)

        assert run(True) == run(False)

    def test_int8_pool_uses_fallback(self):
        """int8 pools (tuple) must not attempt the fp kernel — the engine
        stays oracle-exact with the interpret flag on."""
        from paddle_tpu.models.gpt import GPTConfig, GPTModel
        from paddle_tpu.serving import PagedContinuousBatchingEngine
        paddle.seed(11)
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=96,
                        compute_dtype="float32", kv_cache_dtype="int8")
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        set_flags({"FLAGS_paged_attn_interpret": True})
        try:
            eng = PagedContinuousBatchingEngine(
                model, params, max_slots=2, max_len=32, block_size=8,
                prompt_buckets=[8])
            rid = eng.add_request([5, 17, 3], 6)
            got = eng.run_to_completion(max_ticks=100)
            solo = model.generate(params,
                                  jnp.asarray([[5, 17, 3]], jnp.int32), 6,
                                  greedy=True)
            assert got[rid] == [int(x) for x in np.asarray(solo)[0]]
        finally:
            set_flags({"FLAGS_paged_attn_interpret": False})
            model.__dict__.pop("_serving_programs", None)