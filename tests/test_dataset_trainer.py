"""Dataset trainer loop + role maker + stats tests (SURVEY rows 9, 49, 56)."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _write_files(tmp_path, n_files=3, rows=40, feats=4):
    rng = np.random.RandomState(0)
    files = []
    for i in range(n_files):
        p = tmp_path / f"part-{i}.txt"
        with open(p, "w") as f:
            for _ in range(rows):
                x = rng.standard_normal(feats)
                y = int(x[0] > 0)
                f.write(" ".join(f"{v:.6f}" for v in x) + f" {y}\n")
        files.append(str(p))
    return files


class TestDatasets:
    def test_in_memory_load_shuffle_iterate(self, tmp_path):
        files = _write_files(tmp_path)
        ds = paddle.io.InMemoryDataset()
        ds.set_filelist(files)
        ds.set_batch_size(16)
        ds.set_thread(2)
        ds.load_into_memory()
        assert ds.get_memory_data_size() == 120
        ds.local_shuffle(seed=0)
        batches = list(ds)
        assert sum(b[0].shape[0] for b in batches) == 120
        assert batches[0][0].shape == (16, 4)
        assert batches[0][1].dtype == np.int64
        ds.release_memory()
        with pytest.raises(RuntimeError):
            iter(ds)

    def test_queue_dataset_streams(self, tmp_path):
        files = _write_files(tmp_path, n_files=2, rows=10)
        ds = paddle.io.QueueDataset(capacity=4)
        ds.set_filelist(files)
        ds.set_batch_size(5)
        batches = list(ds)
        assert len(batches) == 4
        # two passes give the same data (restartable stream)
        again = list(ds)
        np.testing.assert_array_equal(batches[0][0], again[0][0])

    def test_custom_parse_fn(self, tmp_path):
        p = tmp_path / "kv.txt"
        p.write_text("a,1\nb,2\nc,3\n")
        ds = paddle.io.QueueDataset()
        ds.set_filelist([str(p)])
        ds.set_batch_size(3)
        ds.set_parse_fn(lambda ln: (np.float32(float(ln.split(",")[1])),))
        (vals,), = list(ds)
        np.testing.assert_array_equal(vals, [1.0, 2.0, 3.0])

    def test_pipe_command_raises(self):
        ds = paddle.io.InMemoryDataset()
        with pytest.raises(NotImplementedError, match="set_parse_fn"):
            ds.set_pipe_command("cat")


class TestTrainFromDataset:
    def test_end_to_end_training(self, tmp_path):
        """The Trainer/DeviceWorker capability: train a model straight from
        files through Executor.train_from_dataset and watch loss fall."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.jit.functional import make_train_step
        import paddle_tpu.nn.functional as F

        files = _write_files(tmp_path, n_files=4, rows=64)
        ds = paddle.io.InMemoryDataset()
        ds.set_filelist(files)
        ds.set_batch_size(32)
        ds.set_thread(2)
        ds.load_into_memory()
        ds.local_shuffle(seed=1)

        paddle.seed(0)
        model = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2))
        opt = paddle.optimizer.SGD(0.5, parameters=model.parameters())
        step, state = make_train_step(
            model, lambda o, y: F.cross_entropy(o, y), opt)
        holder = {"state": state, "i": 0}

        def program(x, y):
            holder["i"] += 1
            holder["state"], (loss, _) = step(
                holder["state"], jax.random.key(holder["i"]),
                np.float32(0.5), (jnp.asarray(x),), (jnp.asarray(y),))
            return loss

        exe = paddle.static.Executor()
        all_losses = []
        for epoch in range(6):
            all_losses += exe.train_from_dataset(program=program, dataset=ds)
        assert all_losses[-1] < all_losses[0] / 2, \
            (all_losses[0], all_losses[-1])


class TestRoleMaker:
    def test_paddle_cloud_collective(self, monkeypatch):
        from paddle_tpu.distributed.fleet.base.role_maker import \
            PaddleCloudRoleMaker
        monkeypatch.setenv("PADDLE_TRAINER_ENDPOINTS",
                           "10.0.0.1:6170,10.0.0.2:6170")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
        rm = PaddleCloudRoleMaker(is_collective=True)
        assert rm.is_worker() and not rm.is_server()
        assert rm.worker_num() == 2
        assert rm.worker_index() == 1
        assert not rm.is_first_worker()
        assert rm.get_trainer_endpoints() == ["10.0.0.1:6170", "10.0.0.2:6170"]

    def test_paddle_cloud_ps_roles(self, monkeypatch):
        from paddle_tpu.distributed.fleet.base.role_maker import \
            PaddleCloudRoleMaker
        monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
        monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST",
                           "10.0.0.9:8000,10.0.0.10:8000")
        monkeypatch.setenv("POD_IP", "10.0.0.10")
        monkeypatch.setenv("PADDLE_PORT", "8000")
        rm = PaddleCloudRoleMaker(is_collective=False)
        assert rm.is_server()
        assert rm.server_index() == 1
        assert rm.server_num() == 2
        monkeypatch.setenv("TRAINING_ROLE", "NONSENSE")
        with pytest.raises(ValueError, match="TRAINING_ROLE"):
            PaddleCloudRoleMaker(is_collective=False).is_worker()

    def test_user_defined(self):
        from paddle_tpu.distributed.fleet.base.role_maker import \
            Role, UserDefinedRoleMaker
        rm = UserDefinedRoleMaker(current_id=2, role=Role.WORKER, worker_num=4)
        assert rm.worker_num() == 4 and rm.worker_index() == 2

    def test_fleet_init_accepts_role_maker(self):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet.base.role_maker import \
            UserDefinedRoleMaker
        st = fleet.DistributedStrategy()
        st.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1}
        fleet.fleet.init(role_maker=UserDefinedRoleMaker(worker_num=1),
                         is_collective=True, strategy=st)
        assert fleet.fleet._role_maker.worker_num() == 1


class TestStats:
    def test_registry_and_op_summary(self):
        from paddle_tpu.utils import stats
        from paddle_tpu.profiler import RecordEvent
        stats.stat_registry().reset()
        stats.stat_add("STAT_reader_batches", 3)
        stats.stat_add("STAT_reader_batches", 2)
        stats.stat_sub("STAT_reader_batches", 1)
        assert stats.get_stat("STAT_reader_batches") == 4
        assert "STAT_reader_batches" in stats.get_all_stats()
        with RecordEvent("my_region"):
            sum(range(1000))
        rows = stats.op_summary()
        assert any(r[0] == "my_region" and r[1] >= 1 for r in rows)
        mem = stats.device_memory_stats(0)
        assert isinstance(mem, dict)


class TestReviewRegressions:
    def test_load_into_memory_propagates_missing_file(self, tmp_path):
        files = _write_files(tmp_path, n_files=2)
        ds = paddle.io.InMemoryDataset()
        ds.set_filelist(files + [str(tmp_path / "missing.txt")])
        ds.set_batch_size(4)
        ds.set_thread(2)
        with pytest.raises(FileNotFoundError):
            ds.load_into_memory()

    def test_queue_dataset_propagates_parse_error(self, tmp_path):
        p = tmp_path / "bad.txt"
        p.write_text("not-a-number here\n")
        ds = paddle.io.QueueDataset()
        ds.set_filelist([str(p)])
        ds.set_batch_size(1)
        with pytest.raises(ValueError):
            list(ds)

    def test_fleet_user_defined_role_maker_not_shadowed(self):
        from paddle_tpu.distributed import fleet
        rm = fleet.UserDefinedRoleMaker(current_id=2, worker_num=4)
        assert rm.worker_index() == 2 and rm.worker_num() == 4

    def test_stats_reset_unseen_counter(self):
        from paddle_tpu.utils import stats
        stats.stat_registry().reset("STAT_never_touched_xyz")
        assert stats.get_stat("STAT_never_touched_xyz") == 0

    def test_infer_from_dataset_tuple_outputs(self, tmp_path):
        files = _write_files(tmp_path, n_files=1, rows=8)
        ds = paddle.io.QueueDataset()
        ds.set_filelist(files)
        ds.set_batch_size(4)
        exe = paddle.static.Executor()
        outs = exe.infer_from_dataset(
            program=lambda x, y: (x * 2.0, y), dataset=ds)
        assert len(outs) == 2 and outs[0].shape == (4, 4)


class TestSecondReviewRegressions:
    def test_threaded_load_is_deterministic(self, tmp_path):
        files = _write_files(tmp_path, n_files=4, rows=20)
        def load():
            ds = paddle.io.InMemoryDataset()
            ds.set_filelist(files)
            ds.set_batch_size(8)
            ds.set_thread(3)
            ds.load_into_memory()
            ds.local_shuffle(seed=7)
            return [b[0] for b in ds]
        a, b = load(), load()
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    def test_queue_dataset_early_break_does_not_leak(self, tmp_path):
        import threading
        files = _write_files(tmp_path, n_files=1, rows=200)
        before = threading.active_count()
        for _ in range(5):
            ds = paddle.io.QueueDataset(capacity=2)
            ds.set_filelist(files)
            ds.set_batch_size(4)
            for batch in ds:
                break  # abandon with the producer mid-stream
        import time
        time.sleep(0.5)  # let producers notice the stop flag
        assert threading.active_count() <= before + 1

    def test_ps_trainer_world_size(self, monkeypatch):
        from paddle_tpu.distributed.fleet.base.role_maker import \
            PaddleCloudRoleMaker
        monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
        monkeypatch.delenv("PADDLE_TRAINER_ENDPOINTS", raising=False)
        rm = PaddleCloudRoleMaker(is_collective=False)
        assert rm.worker_num() == 4

    def test_ps_server_unmatched_endpoint_raises(self, monkeypatch):
        from paddle_tpu.distributed.fleet.base.role_maker import \
            PaddleCloudRoleMaker
        monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
        monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", "10.0.0.9:8000")
        monkeypatch.setenv("POD_IP", "10.9.9.9")
        monkeypatch.setenv("PADDLE_PORT", "8000")
        with pytest.raises(ValueError, match="not in"):
            PaddleCloudRoleMaker(is_collective=False).is_server()


class TestFleetNamespace:
    def test_fleet_passthroughs(self):
        from paddle_tpu.distributed import fleet
        st = fleet.DistributedStrategy()
        st.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1}
        fleet.init(is_collective=True, strategy=st)
        assert fleet.rank() == fleet.worker_index()
        assert fleet.nranks() == fleet.worker_num() == fleet.world_size()
        assert fleet.is_worker() and not fleet.is_server()
        assert isinstance(fleet.worker_endpoints(), list)
        assert isinstance(fleet.worker_endpoints(to_string=True), str)
        assert fleet.node_num() >= 1
        assert len(fleet.local_device_ids()) >= 1
        fleet.init_worker(); fleet.stop_worker()  # no-ops in collective mode
        with pytest.raises(RuntimeError, match="non-goal"):
            fleet.init_server()
        import paddle_tpu.nn as nn
        opt = fleet.distributed_optimizer(
            paddle.optimizer.SGD(0.1, parameters=nn.Linear(2, 2).parameters()))
        assert fleet.get_lr() == pytest.approx(0.1)

    def test_fleet_metrics_and_util(self):
        from paddle_tpu.distributed import fleet
        assert fleet.metrics.sum(np.array([1.0, 2.0])) == 3.0
        assert fleet.metrics.acc(np.array(8.0), np.array(10.0)) == \
            pytest.approx(0.8)
        assert fleet.metrics.rmse(np.array([8.0]), 2) == pytest.approx(2.0)
        # auc on a clean separation: all positives above all negatives
        pos = np.zeros(10); pos[9] = 5
        neg = np.zeros(10); neg[0] = 5
        assert fleet.metrics.auc(pos, neg) == pytest.approx(1.0)
        util = fleet.UtilBase()
        assert util.get_file_shard(["a", "b", "c"]) == ["a", "b", "c"]

    def test_data_generator_protocol(self):
        from paddle_tpu.distributed.fleet import MultiSlotDataGenerator

        class G(MultiSlotDataGenerator):
            def generate_sample(self, line):
                def gen():
                    vals = [float(v) for v in line.split()]
                    yield [("feat", vals), ("label", [int(vals[0] > 0)])]
                return gen

        g = G()
        lines = g.run_from_memory(["1.0 2.0", "-1.0 0.5"])
        assert lines[0] == "2 1.0 2.0 1 1\n"
        assert lines[1] == "2 -1.0 0.5 1 0\n"


class TestFleetReviewRegressions:
    def test_util_wired_and_all_gather(self):
        from paddle_tpu.distributed import fleet
        st = fleet.DistributedStrategy()
        st.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1}
        fleet.init(is_collective=True, strategy=st)
        assert fleet.fleet.util is fleet.fleet.util  # cached instance
        assert fleet.util.all_gather(7) == [7]

    def test_file_shard_remainder_spread(self, monkeypatch):
        from paddle_tpu.distributed import fleet
        monkeypatch.setattr(fleet.fleet, "worker_num", lambda: 4)
        shards = []
        for i in range(4):
            monkeypatch.setattr(fleet.fleet, "worker_index", lambda i=i: i)
            shards.append(fleet.util.get_file_shard(list("abcde")))
        assert [len(s) for s in shards] == [2, 1, 1, 1]
        assert sum(shards, []) == list("abcde")

    def test_save_persistables_layer_roundtrip(self, tmp_path):
        from paddle_tpu.distributed import fleet
        import paddle_tpu.nn as nn
        paddle.seed(0)
        lin = nn.Linear(3, 2)
        fleet.save_persistables(None, str(tmp_path), main_program=lin)
        loaded = fleet.load_model(str(tmp_path))
        np.testing.assert_allclose(np.asarray(loaded["weight"]._data),
                                   np.asarray(lin.weight._data))
        with pytest.raises(ValueError, match="no parameters"):
            fleet.save_persistables(None, str(tmp_path / "x"))

    def test_save_inference_model_requires_program(self, tmp_path):
        from paddle_tpu.distributed import fleet
        with pytest.raises(ValueError, match="main_program"):
            fleet.save_inference_model(None, str(tmp_path / "m"))
