"""End-to-end model tests (reference: tests/book/test_recognize_digits.py —
train tiny models, assert convergence; hapi python/paddle/tests/test_model.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi import Model
from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.metric import Accuracy
from paddle_tpu.optimizer import Adam, Momentum
from paddle_tpu.optimizer.lr import StepDecay

BASE = np.random.RandomState(7).randn(10, 1, 28, 28).astype("float32")


class SynthMNIST(Dataset):
    def __init__(self, n=256, seed=0):
        rng = np.random.RandomState(seed)
        self.y = rng.randint(0, 10, n)
        self.x = BASE[self.y] + 0.3 * rng.randn(n, 1, 28, 28).astype("float32")

    def __getitem__(self, i):
        return self.x[i], np.int64(self.y[i])

    def __len__(self):
        return len(self.y)


class LeNet(nn.Layer):
    """Reference LeNet (python/paddle/vision/models/lenet.py)."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(), nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5), nn.ReLU(), nn.MaxPool2D(2, 2))
        self.fc = nn.Sequential(nn.Linear(400, 120), nn.Linear(120, 84),
                                nn.Linear(84, num_classes))

    def forward(self, x):
        return self.fc(paddle.flatten(self.features(x), 1))


def test_model_fit_evaluate_predict_save_load(tmp_path):
    model = Model(LeNet(), inputs=[None])
    model.prepare(Adam(0.001, parameters=model.parameters()),
                  nn.CrossEntropyLoss(), Accuracy())
    model.fit(SynthMNIST(512, 0), epochs=3, batch_size=64, verbose=0)
    logs = model.evaluate(SynthMNIST(64, 99), batch_size=64)
    assert logs["acc"] > 0.9
    assert logs["loss"] < 1.0

    path = str(tmp_path / "ck")
    model.save(path)
    m2 = Model(LeNet(), inputs=[None])
    m2.prepare(Adam(0.001, parameters=m2.parameters()), nn.CrossEntropyLoss(),
               Accuracy())
    m2.load(path)
    logs2 = m2.evaluate(SynthMNIST(64, 99), batch_size=64)
    assert abs(logs2["acc"] - logs["acc"]) < 1e-6

    preds = model.predict(SynthMNIST(32, 5), batch_size=16, stack_outputs=True)
    assert preds[0].shape == (32, 10)


def test_jit_save_load(tmp_path):
    import paddle_tpu.jit as jit
    net = LeNet()
    path = str(tmp_path / "infer")
    jit.save(net, path, input_spec=[jit.InputSpec([1, 1, 28, 28])])
    tl = jit.load(path)
    x = paddle.randn([1, 1, 28, 28])
    np.testing.assert_allclose(tl(x).numpy(), net(x).numpy(), atol=1e-5)


def test_to_static_decorator():
    import paddle_tpu.jit as jit

    @jit.to_static
    def f(x):
        return x * 2 + 1

    x = paddle.to_tensor([1.0, 2.0])
    np.testing.assert_allclose(f(x).numpy(), [3.0, 5.0])


def test_eager_vs_jit_loss_parity():
    """Same model/data: eager tape-SGD must match the jit functional path
    (the reference's dygraph/static consistency oracle)."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.optimizer import SGD

    x = np.random.randn(32, 10).astype("float32")
    y = np.random.randint(0, 3, 32)

    paddle.seed(11)
    net_e = nn.Sequential(nn.Linear(10, 16), nn.Tanh(), nn.Linear(16, 3))
    paddle.seed(11)
    net_j = nn.Sequential(nn.Linear(10, 16), nn.Tanh(), nn.Linear(16, 3))

    opt_e = SGD(0.1, parameters=net_e.parameters())
    eager_losses = []
    for _ in range(5):
        loss = F.cross_entropy(net_e(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        opt_e.step()
        opt_e.clear_grad()
        eager_losses.append(float(loss))

    model = Model(net_j, inputs=[None])
    model.prepare(SGD(0.1, parameters=net_j.parameters()), nn.CrossEntropyLoss())
    jit_losses = []
    for _ in range(5):
        jit_losses.append(model.train_batch([paddle.to_tensor(x)],
                                            [paddle.to_tensor(y)])[0])
    np.testing.assert_allclose(eager_losses, jit_losses, rtol=2e-4, atol=2e-5)


def test_dataloader():
    ds = SynthMNIST(50)
    dl = DataLoader(ds, batch_size=16, shuffle=True, drop_last=True)
    batches = list(dl)
    assert len(batches) == 3
    assert batches[0][0].shape == [16, 1, 28, 28]
    dl2 = DataLoader(ds, batch_size=16, drop_last=False)
    assert len(list(dl2)) == 4


def test_optimizer_state_roundtrip(tmp_path):
    lin = nn.Linear(4, 2)
    opt = Adam(0.01, parameters=lin.parameters())
    loss = lin(paddle.randn([8, 4])).mean()
    loss.backward()
    opt.step()
    sd = opt.state_dict()
    opt2 = Adam(0.01, parameters=lin.parameters())
    opt2.set_state_dict(sd)
    assert opt2._step_count == 1


def test_lr_schedulers():
    from paddle_tpu.optimizer.lr import (CosineAnnealingDecay, LinearWarmup,
                                         MultiStepDecay, NoamDecay, PiecewiseDecay,
                                         PolynomialDecay, ReduceOnPlateau)
    s = StepDecay(0.1, step_size=2, gamma=0.5)
    lrs = []
    for _ in range(5):
        lrs.append(s())
        s.step()
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025])
    w = LinearWarmup(0.1, warmup_steps=4, start_lr=0.0, end_lr=0.1)
    assert w() == 0.0
    w.step()
    assert abs(w() - 0.025) < 1e-9
    p = ReduceOnPlateau(0.1, patience=1)
    p.step(1.0)
    p.step(1.0)
    p.step(1.0)
    assert p() < 0.1 + 1e-12


class TestGradAccumulation:
    def test_accum_equals_large_batch(self):
        """N micro-batches with accumulation == one N-times-larger batch
        (SGD makes the equivalence exact up to float assoc)."""
        import numpy as np
        import jax
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.jit.functional import (make_accum_train_step,
                                               make_train_step)

        rng = np.random.RandomState(0)
        X = rng.standard_normal((32, 16)).astype("float32")
        y = (X[:, 0] > 0).astype("int64")

        def build():
            paddle.seed(7)
            net = nn.Sequential(nn.Linear(16, 8), nn.ReLU(), nn.Linear(8, 2))
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=net.parameters())
            return net, opt

        key = jax.random.key(0)
        lr = np.float32(0.1)

        net_a, opt_a = build()
        step_a, state_a = make_accum_train_step(
            net_a, paddle.nn.CrossEntropyLoss(), opt_a, accum_steps=4)
        for i in range(4):
            state_a, _ = step_a(state_a, key, lr,
                                [X[i * 8:(i + 1) * 8]], [y[i * 8:(i + 1) * 8]])

        net_b, opt_b = build()
        step_b, state_b = make_train_step(net_b, paddle.nn.CrossEntropyLoss(),
                                          opt_b)
        state_b, _ = step_b(state_b, key, lr, [X], [y])

        for name in state_a["params"]:
            np.testing.assert_allclose(np.asarray(state_a["params"][name]),
                                       np.asarray(state_b["params"][name]),
                                       rtol=1e-5, atol=1e-6)
        # counter reset after the apply step
        assert int(state_a["acc_count"]) == 0

    def test_fit_accepts_accumulate_grad_batches(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 2))
        model = paddle.Model(net)
        model.prepare(paddle.optimizer.SGD(0.2, parameters=net.parameters()),
                      paddle.nn.CrossEntropyLoss())
        rng = np.random.RandomState(0)
        X = rng.standard_normal((64, 8)).astype("float32")
        y = (X[:, 0] > 0).astype("int64")
        from paddle_tpu.io import DataLoader, Dataset

        class DS(Dataset):
            def __getitem__(self, i):
                return X[i], y[i]

            def __len__(self):
                return 64

        model.fit(DS(), batch_size=8, epochs=2, verbose=0,
                  accumulate_grad_batches=4)
        res = model.evaluate(DataLoader(DS(), batch_size=8), verbose=0)
        assert np.isfinite(res["loss"])


class TestCallbacksBehavior:
    """Behavioral callback tests (previously surface-only; ≙ reference
    test_callbacks.py)."""

    def _fit(self, callbacks, epochs=6, with_eval=True):
        import paddle_tpu.nn as nn
        from paddle_tpu.hapi import Model
        from paddle_tpu.metric import Accuracy

        paddle.seed(0)
        rng = np.random.RandomState(0)
        X = rng.randn(64, 8).astype("float32")
        yv = (rng.rand(64) > 0.5).astype("int64")
        data = [(X[i:i + 16], yv[i:i + 16]) for i in range(0, 64, 16)]
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
        model = Model(net)
        model.prepare(paddle.optimizer.SGD(0.1, parameters=net.parameters()),
                      nn.CrossEntropyLoss(), Accuracy())
        model.fit(data, eval_data=data if with_eval else None, epochs=epochs,
                  verbose=0, callbacks=callbacks)
        return model

    def test_early_stopping_stops(self):
        from paddle_tpu.hapi.callbacks import EarlyStopping
        es = EarlyStopping(monitor="acc", mode="max", patience=1,
                           baseline=1.1,  # unreachable -> every epoch "worse"
                           verbose=0, save_best_model=False)
        es.best = 1.1
        self._fit([es], epochs=8)
        assert es.stop_training  # fired well before 8 epochs

    def test_model_checkpoint_writes(self, tmp_path):
        import os
        from paddle_tpu.hapi.callbacks import ModelCheckpoint
        d = str(tmp_path / "ckpts")
        os.makedirs(d, exist_ok=True)
        self._fit([ModelCheckpoint(save_freq=2, save_dir=d)], epochs=3)
        names = set(os.listdir(os.path.dirname(os.path.join(d, "x"))))
        assert any(n.startswith("final") for n in names), names
        assert any(n.startswith("0") for n in names), names

    def test_reduce_lr_on_plateau_callback(self):
        from paddle_tpu.hapi.callbacks import ReduceLROnPlateau
        cb = ReduceLROnPlateau(monitor="acc", mode="max", patience=0,
                               factor=0.5, verbose=0)
        model = self._fit([cb], epochs=4)
        lr = model._optimizer.get_lr() if hasattr(model._optimizer, "get_lr") \
            else model._optimizer._learning_rate
        assert float(lr) < 0.1  # reduced at least once from the 0.1 base
