"""Native TCP store (csrc/kv_store.cpp + distributed/store.py) and the
elastic manager over it.  ≙ reference fleet/elastic/manager.py etcd flows
(registration, heartbeat lease, membership watch) and gen_comm_id_helper.cc's
TCP rendezvous — here against the framework's own single-binary store."""

import json
import struct
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.store import (FileStore, StoreServer, TCPStore,
                                          make_store)


@pytest.fixture(scope="module")
def server():
    srv = StoreServer(port=0)
    yield srv
    srv.stop()


@pytest.fixture
def store(server):
    st = TCPStore("127.0.0.1", server.port, timeout=10.0)
    yield st
    st.close()


class TestTCPStore:
    def test_set_get_delete(self, store):
        assert store.get("missing") is None
        store.set("k1", b"hello")
        assert store.get("k1") == b"hello"
        store.set("k1", b"world")          # overwrite
        assert store.get("k1") == b"world"
        store.delete("k1")
        assert store.get("k1") is None

    def test_add_atomic_counter(self, server):
        stores = [TCPStore("127.0.0.1", server.port) for _ in range(4)]
        results = []

        def bump(st):
            for _ in range(25):
                results.append(st.add("ctr"))

        threads = [threading.Thread(target=bump, args=(s,)) for s in stores]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # 100 increments, every value unique, final == 100
        assert sorted(results) == list(range(1, 101))
        assert stores[0].add("ctr", 0) == 100
        for s in stores:
            s.close()

    def test_wait_blocks_until_set(self, server):
        waiter = TCPStore("127.0.0.1", server.port)
        setter = TCPStore("127.0.0.1", server.port)
        got = {}

        def wait():
            got["val"] = waiter.wait("gate", timeout=10.0)

        t = threading.Thread(target=wait)
        t.start()
        time.sleep(0.3)
        assert t.is_alive()                 # parked server-side, no value yet
        setter.set("gate", b"open")
        t.join(timeout=5.0)
        assert got["val"] == b"open"
        waiter.close()
        setter.close()

    def test_wait_existing_returns_immediately(self, store):
        store.set("ready", b"1")
        t0 = time.time()
        assert store.wait("ready", timeout=5.0) == b"1"
        assert time.time() - t0 < 1.0

    def test_list_prefix(self, store):
        for i in range(3):
            store.set(f"pfx-{i}", str(i).encode())
        store.set("other", b"x")
        got = store.list_prefix("pfx-")
        assert got == {"pfx-0": b"0", "pfx-1": b"1", "pfx-2": b"2"}

    def test_large_value_roundtrip(self, store):
        blob = np.random.RandomState(0).bytes(1 << 20)  # 1 MiB
        store.set("blob", blob)
        assert store.get("blob") == blob

    def test_make_store_url(self, server):
        st = make_store(f"tcp://127.0.0.1:{server.port}")
        assert isinstance(st, TCPStore)
        st.set("via-url", b"y")
        assert st.get("via-url") == b"y"
        st.close()

    def test_add_on_string_value_is_protocol_error(self, server):
        """ADD on a key SET to a non-8-byte value must not silently clobber
        it with a counter; the server closes the connection as malformed and
        the value survives (ADVICE r3: kv_store.cpp ADD type confusion)."""
        st = TCPStore("127.0.0.1", server.port)
        st.set("strkey", b"not-a-counter")
        with pytest.raises(OSError):
            st.add("strkey")           # server drops the malformed connection
        st2 = TCPStore("127.0.0.1", server.port)
        assert st2.get("strkey") == b"not-a-counter"   # value untouched
        st.close()
        st2.close()


class TestFileStoreParity:
    """FileStore implements the same contract (dir backend)."""

    def test_same_contract(self, tmp_path):
        st = FileStore(str(tmp_path))
        assert st.get("nope") is None
        st.set("a", b"1")
        assert st.get("a") == b"1"
        assert st.add("n", 5) == 5
        assert st.add("n", -2) == 3
        assert st.list_prefix("a") == {"a": b"1"}
        st.delete("a")
        assert st.get("a") is None
        assert st.wait("n", timeout=1.0) == struct.pack("<q", 3)
        with pytest.raises(TimeoutError):
            st.wait("never", timeout=0.2)

    def test_add_on_string_value_is_error(self, tmp_path):
        # same contract as TCPStore: protocol error (OSError), value intact
        st = FileStore(str(tmp_path))
        st.set("strkey", b"not-a-counter")
        with pytest.raises(OSError):
            st.add("strkey")
        assert st.get("strkey") == b"not-a-counter"

    def test_add_lock_released_on_holder_sigkill(self, tmp_path):
        """A lock holder SIGKILLed mid-section (the exact fault elastic
        exists for) must not wedge or double-admit later adders: flock is
        kernel-released on death, unlike the old mtime-staleness steal
        (ADVICE r3: FileStore.add TOCTOU race)."""
        import os
        import signal
        import subprocess
        import sys
        import textwrap
        st = FileStore(str(tmp_path))
        st.add("c", 7)
        holder = subprocess.Popen(
            [sys.executable, "-c", textwrap.dedent(f"""
                import fcntl, os, time
                fd = os.open({str(tmp_path)!r} + "/c.lock",
                             os.O_CREAT | os.O_WRONLY)
                fcntl.flock(fd, fcntl.LOCK_EX)
                print("locked", flush=True)
                time.sleep(60)
            """)], stdout=subprocess.PIPE)
        assert holder.stdout.readline().strip() == b"locked"
        os.kill(holder.pid, signal.SIGKILL)
        holder.wait()
        t0 = time.time()
        assert st.add("c", 1) == 8          # no stall, no lost increment
        assert time.time() - t0 < 2.0


class TestElasticOverTCP:
    def test_membership_and_restart_decision(self, server):
        """Two ranks register via tcp://; one dies (lease expires) ⇒ the
        survivor's exit_code is the restart protocol code (101)."""
        from paddle_tpu.distributed.fleet.elastic import (ELASTIC_EXIT_CODE,
                                                          ElasticManager)

        url = f"tcp://127.0.0.1:{server.port}"
        m0 = ElasticManager(url, rank=0, heartbeat_interval=0.1, lease_ttl=0.8)
        m1 = ElasticManager(url, rank=1, heartbeat_interval=0.1, lease_ttl=0.8)
        m0.register()
        m1.register()
        time.sleep(0.3)
        assert m0.alive_ranks() == [0, 1]
        assert m0.exit_code() is None       # baseline snapshot, stable world

        m1.stop()                           # rank 1 leaves (deletes its lease)
        deadline = time.time() + 5.0
        while m0.alive_ranks() != [0] and time.time() < deadline:
            time.sleep(0.1)
        assert m0.alive_ranks() == [0]
        assert m0.exit_code() == ELASTIC_EXIT_CODE
        m0.stop()


class TestConnectionRecovery:
    def test_wait_timeout_then_reuse(self, server):
        """A timed-out WAIT poisons the wire framing; the client must drop
        and redial so the next request still gets a correct reply."""
        st = TCPStore("127.0.0.1", server.port)
        with pytest.raises(OSError):
            st.wait("never-set-key", timeout=0.3)
        st.set("after-timeout", b"ok")          # redialed transparently
        assert st.get("after-timeout") == b"ok"
        # and the counter protocol still frames correctly
        assert st.add("recover-ctr") == 1
        st.close()

    def test_wait_none_blocks_past_default(self, server):
        """wait(timeout=None) must block indefinitely (not the 60s default);
        proven at small scale with a 1s-timeout client waiting 2s."""
        st = TCPStore("127.0.0.1", server.port, timeout=1.0)
        setter = TCPStore("127.0.0.1", server.port)
        got = {}

        def wait():
            got["val"] = st.wait("slow-gate", timeout=None)

        t = threading.Thread(target=wait)
        t.start()
        time.sleep(2.0)                          # > client default timeout
        assert t.is_alive()
        setter.set("slow-gate", b"v")
        t.join(timeout=5.0)
        assert got["val"] == b"v"
        st.close()
        setter.close()
