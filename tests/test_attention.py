"""Flash-attention kernel tests (Pallas interpret mode on CPU).

OpTest-style oracle comparisons (reference op_test.py:277 methodology):
forward and analytic gradients of the Pallas kernels vs the dense XLA
reference at fp32, plus dropout determinism and an O(L) memory assertion
(no (L, L) intermediate in the backward jaxpr — the round-1 backward vjp'd
through dense attention and materialized it).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.ops.attention as A


def _rand_qkv(B=2, L=256, H=2, D=64, seed=0, dtype=jnp.float32):
    r = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(r.standard_normal((B, L, H, D)).astype(np.float32),
                             dtype=dtype)
    return mk(), mk(), mk()


def _flash(q, k, v, causal=False, key_mask=None, dropout_p=0.0, seed=0):
    B, L = q.shape[0], q.shape[1]
    km = (jnp.zeros((B, L), jnp.float32) if key_mask is None
          else key_mask.astype(jnp.float32))
    sd = jnp.full((1,), seed, jnp.uint32)
    scale = 1.0 / np.sqrt(q.shape[-1])
    return A._flash_attention(q, k, v, km, sd, causal, scale, dropout_p, 128)


class TestFlashForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        q, k, v = _rand_qkv()
        out = _flash(q, k, v, causal=causal)
        ref = A.dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_key_padding_mask_matches_dense(self):
        q, k, v = _rand_qkv()
        B, L = q.shape[0], q.shape[1]
        r = np.random.RandomState(1)
        valid = r.rand(B, L) > 0.3
        valid[:, 0] = True  # every row keeps at least one key
        km = jnp.asarray(np.where(valid, 0.0, -1e30).astype(np.float32))
        out = _flash(q, k, v, key_mask=km)
        ref = A.dense_attention(q, k, v, mask=km[:, None, None, :])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


class TestFlashBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_dense(self, causal):
        q, k, v = _rand_qkv(L=256)

        def loss_flash(q, k, v):
            return jnp.sum(_flash(q, k, v, causal=causal) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(A.dense_attention(q, k, v, causal=causal) ** 2)

        g_f = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_d = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_f, g_d, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5, rtol=5e-5,
                                       err_msg=f"d{name} mismatch")

    def test_grads_match_dense_with_mask(self):
        q, k, v = _rand_qkv()
        B, L = q.shape[0], q.shape[1]
        r = np.random.RandomState(2)
        valid = r.rand(B, L) > 0.3
        valid[:, 0] = True
        km = jnp.asarray(np.where(valid, 0.0, -1e30).astype(np.float32))

        g_f = jax.grad(lambda q, k, v: jnp.sum(
            _flash(q, k, v, key_mask=km) ** 2), argnums=(0, 1, 2))(q, k, v)
        g_d = jax.grad(lambda q, k, v: jnp.sum(
            A.dense_attention(q, k, v, mask=km[:, None, None, :]) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_f, g_d, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5, rtol=5e-5,
                                       err_msg=f"d{name} mismatch")

    def test_no_quadratic_buffer_in_backward(self):
        """The VERDICT-cited regression: round-1 backward materialized the
        (B,H,L,L) score matrix.  Walk every aval in the grad jaxpr at L=8192
        and assert nothing quadratic in L exists."""
        B, L, H, D = 1, 8192, 1, 64
        q = jax.ShapeDtypeStruct((B, L, H, D), jnp.float32)

        def loss(q, k, v):
            return jnp.sum(_flash(q, k, v, causal=True))

        jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, q, q)
        limit = L * D * 16  # biggest legitimate buffer family, with slack

        def walk(jx):
            for eqn in jx.eqns:
                for var in eqn.outvars:
                    sz = int(np.prod(var.aval.shape)) if var.aval.shape else 1
                    assert sz < L * L, \
                        f"quadratic buffer {var.aval.shape} from {eqn.primitive}"
                    assert sz <= limit, \
                        f"oversized buffer {var.aval.shape} from {eqn.primitive}"
                for sub in eqn.params.values():
                    if hasattr(sub, "jaxpr"):
                        walk(sub.jaxpr)
        walk(jaxpr.jaxpr)

    def test_l32k_linear_memory(self):
        """L=32768 long-context bound (VERDICT r2 #8): the full fwd+bwd jaxpr
        stays O(L) — no aval anywhere near L*L, and the total live-buffer
        bound fits a single chip's HBM at bf16."""
        B, L, H, D = 1, 32768, 8, 64
        q = jax.ShapeDtypeStruct((B, L, H, D), jnp.bfloat16)

        def loss(q, k, v):
            return jnp.sum(_flash(q, k, v, causal=True).astype(jnp.float32))

        jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, q, q)
        limit = L * D * H * 16

        def walk(jx):
            for eqn in jx.eqns:
                for var in eqn.outvars:
                    sz = int(np.prod(var.aval.shape)) if var.aval.shape else 1
                    assert sz < L * L, \
                        f"quadratic buffer {var.aval.shape} from {eqn.primitive}"
                    assert sz <= limit, \
                        f"oversized buffer {var.aval.shape} from {eqn.primitive}"
                for sub in eqn.params.values():
                    if hasattr(sub, "jaxpr"):
                        walk(sub.jaxpr)
        walk(jaxpr.jaxpr)


class TestFlashDropout:
    def test_deterministic_and_scaled(self):
        q, k, v = _rand_qkv()
        o1 = _flash(q, k, v, dropout_p=0.5, seed=7)
        o2 = _flash(q, k, v, dropout_p=0.5, seed=7)
        o3 = _flash(q, k, v, dropout_p=0.5, seed=8)
        np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
        assert not np.allclose(np.asarray(o1), np.asarray(o3))
        # E[dropout(P)] = P, so the mean output is near the no-dropout one
        base = _flash(q, k, v)
        assert np.abs(np.asarray(o1).mean() - np.asarray(base).mean()) < 0.05

    @pytest.mark.parametrize("argnum,name", [(0, "q"), (1, "k"), (2, "v")])
    def test_vjp_consistent_with_fd(self, argnum, name):
        """Finite-difference check for dQ, dK AND dV under dropout: the
        keep-mask is position-based, so f is locally smooth in q/k and
        linear in v, and central differences match the analytic vjp."""
        q, k, v = _rand_qkv(B=1, L=128, H=1, D=64)
        c = jnp.asarray(np.random.RandomState(3)
                        .standard_normal(q.shape).astype(np.float32))

        def f(*args):
            return jnp.sum(_flash(*args, dropout_p=0.3, seed=5) * c)

        args = [q, k, v]
        g = jax.grad(f, argnums=argnum)(*args)
        eps = 1e-3
        d = jnp.asarray(np.random.RandomState(4)
                        .standard_normal(args[argnum].shape).astype(np.float32))
        hi = list(args); hi[argnum] = args[argnum] + eps * d
        lo = list(args); lo[argnum] = args[argnum] - eps * d
        fd = (f(*hi) - f(*lo)) / (2 * eps)
        analytic = jnp.sum(g * d)
        np.testing.assert_allclose(float(fd), float(analytic), rtol=5e-3,
                                   err_msg=f"d{name} FD mismatch")


class TestSDPARouting:
    def test_bert_padding_mask_uses_flash(self, monkeypatch):
        """(B,1,1,L) additive masks must route to the flash kernel, not the
        dense fallback (VERDICT weak #3)."""
        calls = {}
        orig = A.flash_attention

        def spy(*args, **kw):
            calls["flash"] = True
            return orig(*args, **kw)

        monkeypatch.setattr(A, "flash_attention", spy)
        import paddle_tpu as paddle
        q = paddle.to_tensor(np.random.RandomState(0)
                             .standard_normal((2, 128, 2, 32)).astype(np.float32))
        mask = np.zeros((2, 1, 1, 128), np.float32)
        mask[:, :, :, 100:] = -1e30
        out = A.scaled_dot_product_attention(q, q, q,
                                             attn_mask=paddle.to_tensor(mask))
        assert calls.get("flash"), "padding mask fell back to dense"
        assert np.isfinite(np.asarray(out._data)).all()
