"""int8 KV cache (kv_cache_dtype="int8"): per-(position, head) symmetric
quantization halves decode-loop cache HBM traffic.

≙ the reference's fused_multi_transformer_int8 CacheKV quant/dequant round
trip; here the quantized pair (values_int8, scales) flows through the SAME
write_cache/cached_attention call sites as the fp cache (tuple-dispatch),
so every decode feature — generation, serving engine, beam reorder —
works on both formats."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models._decode import (dequantize_cache, quantize_kv,
                                       write_cache)
from paddle_tpu.models.gpt import GPTConfig, GPTModel


def _mk(kv_dtype, seed=21):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=64,
                    compute_dtype="float32", kv_cache_dtype=kv_dtype)
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    return model, params


class TestQuantPrimitives:
    def test_roundtrip_error_bound(self):
        """Symmetric int8 over the last axis: relative reconstruction error
        per vector is bounded by the quantization step (amax/127)."""
        x = jax.random.normal(jax.random.key(0), (3, 5, 4, 16))
        q, s = quantize_kv(x)
        assert q.dtype == jnp.int8 and s.shape == x.shape[:-1]
        back = np.asarray(dequantize_cache((q, s), jnp.float32))
        err = np.abs(back - np.asarray(x))
        bound = np.asarray(s)[..., None] * 0.5 + 1e-7   # half a step
        assert (err <= bound).all()

    def test_write_cache_tuple_dispatch(self):
        """write_cache on a quantized pair quantizes the chunk and writes
        both planes, scalar and per-row t forms."""
        cache = (jnp.zeros((2, 8, 4, 16), jnp.int8),
                 jnp.zeros((2, 8, 4), jnp.float32))
        chunk = jax.random.normal(jax.random.key(1), (2, 2, 4, 16))
        out = write_cache(cache, chunk, 3)
        back = np.asarray(dequantize_cache(out, jnp.float32))[:, 3:5]
        np.testing.assert_allclose(back, np.asarray(chunk), atol=0.05)
        # per-row t
        out2 = write_cache(cache, chunk, jnp.asarray([1, 5]))
        b2 = np.asarray(dequantize_cache(out2, jnp.float32))
        np.testing.assert_allclose(b2[0, 1:3], np.asarray(chunk)[0], atol=0.05)
        np.testing.assert_allclose(b2[1, 5:7], np.asarray(chunk)[1], atol=0.05)


class TestInt8Generation:
    def test_cache_buffers_are_int8(self):
        model, _ = _mk("int8")
        (ck, cv) = model.init_cache(2, 16)
        assert ck[0].dtype == jnp.int8 and ck[1].dtype == jnp.float32
        assert ck[0].shape == (2, 2, 16, 4, 8) and ck[1].shape == (2, 2, 16, 4)
        # the int8 pair is ~half the bf16 cache bytes (1 + 4/hd vs 2)
        int8_bytes = ck[0].size + 4 * ck[1].size
        bf16_bytes = 2 * ck[0].size
        assert int8_bytes < 0.8 * bf16_bytes

    def test_decode_logits_close_to_fp_cache(self):
        """Same weights, fp vs int8 cache: per-step decode logits must stay
        within quantization noise (the serving accuracy tradeoff, bounded)."""
        model_fp, params = _mk(None)
        model_q, _ = _mk("int8")   # same seed -> identical weights
        ids = jnp.asarray([[5, 17, 3, 41, 8, 2, 30, 11]], jnp.int32)

        def step_logits(model):
            h, caches = model.prefill(params, ids, 16)
            logits = [np.asarray(model.decode_logits(params, h[:, -1:]))]
            tok = jnp.argmax(logits[-1][:, -1], -1).astype(jnp.int32)
            for i in range(4):
                t = ids.shape[1] + i
                h1 = model._embed_one(params, tok, t)
                h1, caches = model.decode_step(params, h1, caches, t)
                logits.append(np.asarray(model.decode_logits(params, h1)))
                tok = jnp.argmax(logits[-1][:, -1], -1).astype(jnp.int32)
            return np.concatenate(logits, axis=1)

        lf = step_logits(model_fp)
        lq = step_logits(model_q)
        # int8 noise is small relative to the logit scale
        denom = np.maximum(np.abs(lf).max(), 1.0)
        assert np.abs(lf - lq).max() / denom < 0.05, \
            np.abs(lf - lq).max() / denom

    def test_generate_runs_and_matches_fp_tokens(self):
        """Greedy tokens under the int8 cache match the fp cache for this
        model/prompt (well-separated argmax margins; logit closeness is the
        guaranteed contract, checked above)."""
        model_fp, params = _mk(None)
        model_q, _ = _mk("int8")
        ids = jnp.asarray([[5, 17, 3]], jnp.int32)
        out_fp = np.asarray(model_fp.generate(params, ids, 8, greedy=True))
        out_q = np.asarray(model_q.generate(params, ids, 8, greedy=True))
        assert out_q.shape == out_fp.shape
        assert (out_fp == out_q).mean() >= 0.75, (out_fp, out_q)

    def test_beam_search_works_with_int8_cache(self):
        """Beam reorder tree_maps over the quantized pair (scale plane is
        4D — the reorder must be rank-generic)."""
        model_q, params = _mk("int8")
        ids = jnp.asarray([[5, 17, 3]], jnp.int32)
        out = model_q.generate_beam(params, ids, 5, num_beams=3)
        seq = out[0] if isinstance(out, tuple) else out
        assert np.asarray(seq).shape[-1] == 5


class TestInt8Serving:
    def test_engine_serves_int8_model(self):
        """The continuous-batching engine runs unchanged on an int8-cache
        model (tree-aware slot writes); outputs match the int8 model's own
        solo generation exactly."""
        from paddle_tpu.serving import ContinuousBatchingEngine
        model_q, params = _mk("int8")
        eng = ContinuousBatchingEngine(model_q, params, max_slots=2,
                                       max_len=32, prompt_buckets=[8],
                                       ticks_per_sync=2)
        prompts = [[5, 17, 3], [40, 2], [9, 9, 1]]
        rids = [eng.add_request(p, 6) for p in prompts]
        got = eng.run_to_completion(max_ticks=100)
        for rid, p in zip(rids, prompts):
            solo = model_q.generate(params, jnp.asarray([p], jnp.int32), 6,
                                    greedy=True)
            assert got[rid] == [int(t) for t in np.asarray(solo)[0]]
        assert eng.caches[0][0].dtype == jnp.int8
