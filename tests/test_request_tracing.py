"""End-to-end request tracing (ISSUE 10): TraceContext minting and
propagation gateway → engines, cross-source stitching via
RequestTraceIndex (including the acceptance e2e — a quarantine-rerouted
request reconstructs as ONE trace spanning both replicas with no orphan
spans), the ops-server /requests + /request/<id> routes, chrome flow
events, MFU/roofline attribution at the compile seams, and the PR 4-style
off-path purity pin extended to trace-context plumbing."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.gateway import ServingGateway
from paddle_tpu.models.gpt import GPTConfig, GPTModel
from paddle_tpu.serving import (ContinuousBatchingEngine,
                                PagedContinuousBatchingEngine)
from paddle_tpu.telemetry import (RequestTraceIndex, TraceContext, Tracer,
                                  events_to_chrome)


@pytest.fixture(scope="module")
def model_and_params():
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=96,
                    compute_dtype="float32")
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    return model, params


def _paged(model, params, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("block_size", 4)
    kw.setdefault("prompt_buckets", [8, 16])
    kw.setdefault("tracer", Tracer())
    return PagedContinuousBatchingEngine(model, params, **kw)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# --------------------------------------------------------------- context --

class TestTraceContext:
    def test_root_and_child_identity(self):
        root = TraceContext.root()
        child = root.child()
        assert child.trace_id == root.trace_id
        assert child.parent_span_id == root.span_id
        assert child.span_id != root.span_id
        assert TraceContext.root().trace_id != root.trace_id
        d = child.to_dict()
        assert set(d) == {"trace_id", "span_id", "parent_span_id"}

    def test_bind_attaches_to_request_events_and_unbinds_on_terminal(self):
        tr = Tracer()
        ctx = TraceContext.root().child()
        tr.bind_trace(7, ctx)
        tr.request_event(7, "queued", prompt_len=3)
        tr.request_event(7, "retired")
        evs = tr.events("request")
        assert all(e["trace_id"] == ctx.trace_id for e in evs)
        assert all(e["span_id"] == ctx.span_id for e in evs)
        assert all(e["parent_span_id"] == ctx.parent_span_id for e in evs)
        assert tr.trace_of(7) is None            # dropped at terminal
        tr.request_event(8, "queued")            # unbound rid: no fields
        assert "trace_id" not in tr.events("request")[-1]

    def test_engine_add_request_binds_and_preemption_keeps_binding(
            self, model_and_params):
        model, params = model_and_params
        eng = _paged(model, params, num_blocks=6)
        ctx = TraceContext.root().child()
        rid = eng.add_request([5, 17, 3], 4, trace_ctx=ctx)
        eng.run_to_completion(max_ticks=100)
        evs = [e for e in eng.tracer.events("request") if e["rid"] == rid]
        assert evs and all(e.get("trace_id") == ctx.trace_id for e in evs)
        whats = [e["what"] for e in evs]
        assert whats[0] == "queued" and whats[-1] == "retired"


# ------------------------------------------------------- stitched traces --

def _stitched(gw, names):
    idx = RequestTraceIndex()
    idx.add_source(gw.tracer, "gateway")
    for n in names:
        idx.add_source(gw.replica(n).engine.tracer, n)
    return idx


def _assert_well_formed(trace):
    """Every span parented, exactly one root, no dangling parents."""
    spans = trace["spans"]
    ids = {s["span_id"] for s in spans}
    roots = [s for s in spans if s["parent_span_id"] is None]
    assert len(roots) == 1 and roots[0]["name"] == "request"
    orphans = [s for s in spans if s["parent_span_id"] is not None
               and s["parent_span_id"] not in ids]
    assert not orphans, orphans


class TestStitchedTraces:
    def test_quarantine_reroute_yields_one_trace_both_replicas(
            self, model_and_params):
        """THE acceptance e2e: a request that survives a quarantine
        reroute reconstructs as ONE stitched trace via the index (and
        GET /request/<id>), covering BOTH replicas, every span parented,
        no orphans."""
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock(), stall_threshold_s=5.0,
                            tracer=Tracer())
        gw.add_replica(_paged(model, params), "a")
        gw.add_replica(_paged(model, params), "b")
        r = gw.submit([5, 17, 3], 8)
        assert r.trace is not None
        gw.step()
        victim = r.replica
        rep = gw.replica(victim)
        rep.engine.tracer.last_event_age_s = lambda: 99.0    # wedge it
        gw.step()
        assert rep.state == "quarantined"
        gw.run_to_completion(max_ticks=300)
        assert r.status == "finished" and r.replica != victim

        idx = _stitched(gw, ["a", "b"])
        trace = idx.trace(r.trace.trace_id)
        assert trace is not None
        _assert_well_formed(trace)
        assert trace["status"] == "finished"
        assert trace["gid"] == r.gid
        # one attempt span per dispatch, one per replica — both present
        attempts = [s for s in trace["spans"]
                    if s["name"].startswith("attempt@")]
        assert {a["replica"] for a in attempts} == {"a", "b"}
        # the surviving attempt has the full phase ladder
        survivor = [s for s in trace["spans"]
                    if s["parent_span_id"] in
                    {a["span_id"] for a in attempts
                     if a["replica"] == r.replica}]
        assert {"queued", "prefill", "decode"} <= \
            {s["name"] for s in survivor}
        # the event sequence shows the journey: dispatch -> reroute ->
        # dispatch -> finish, all on one trace_id
        whats = [e.get("what") for e in trace["events"]
                 if e.get("kind") == "gateway"]
        assert whats.count("dispatch") == 2
        assert "reroute" in whats and whats[-1] == "finish"
        assert {e["trace_id"] for e in trace["events"]} == \
            {r.trace.trace_id}

    def test_recent_ring_summaries(self, model_and_params):
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock(), tracer=Tracer(),
                            max_queue_depth=1)
        gw.add_replica(_paged(model, params), "a")
        ok = gw.submit([5, 17, 3], 4)
        shed = [gw.submit([1, 2], 3) for _ in range(3)][-1]
        gw.run_to_completion(max_ticks=200)
        recents = _stitched(gw, ["a"]).recent(10)
        by_id = {x["trace_id"]: x for x in recents}
        assert by_id[ok.trace.trace_id]["status"] == "finished"
        assert by_id[ok.trace.trace_id]["replicas"] == ["a"]
        assert by_id[shed.trace.trace_id]["status"] == "shed"
        # newest first, bounded
        assert len(_stitched(gw, ["a"]).recent(2)) == 2
        # a shed trace still stitches (root span only, well-formed)
        shed_trace = _stitched(gw, ["a"]).trace(shed.trace.trace_id)
        _assert_well_formed(shed_trace)
        assert shed_trace["status"] == "shed"

    def test_ops_server_requests_routes(self, model_and_params):
        from paddle_tpu.ops_server import OpsServer
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock(), tracer=Tracer())
        gw.add_replica(_paged(model, params), "a")
        r = gw.submit([5, 17, 3], 4)
        gw.run_to_completion(max_ticks=200)
        srv = OpsServer()
        srv.attach(gw)
        srv.attach(gw.replica("a").engine)
        url = srv.start()
        try:
            recents = json.loads(urllib.request.urlopen(
                url + "/requests?n=5", timeout=10).read())
            assert recents["requests"][0]["trace_id"] == r.trace.trace_id
            one = json.loads(urllib.request.urlopen(
                url + f"/request/{r.trace.trace_id}", timeout=10).read())
            _assert_well_formed(one)
            assert one["status"] == "finished"
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url + "/request/deadbeef",
                                       timeout=10)
            assert ei.value.code == 404
        finally:
            srv.stop()

    def test_ops_server_gateway_only_attach_serves_full_ladder(
            self, model_and_params):
        """attach(gateway) ALONE must serve the full stitched timeline:
        replica engine tracers are enumerated live at query time, so the
        phase ladder (queued/prefill/decode) and BOTH replicas of a
        quarantine reroute appear without attaching any engine — and a
        drain-swapped replacement would, too."""
        from paddle_tpu.ops_server import OpsServer
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock(), stall_threshold_s=5.0,
                            tracer=Tracer())
        gw.add_replica(_paged(model, params), "a")
        gw.add_replica(_paged(model, params), "b")
        r = gw.submit([5, 17, 3], 8)
        gw.step()
        victim = r.replica
        gw.replica(victim).engine.tracer.last_event_age_s = lambda: 99.0
        gw.step()
        gw.run_to_completion(max_ticks=300)
        assert r.status == "finished" and r.replica != victim
        srv = OpsServer()
        srv.attach(gw)                      # nothing else
        url = srv.start()
        try:
            one = json.loads(urllib.request.urlopen(
                url + f"/request/{r.trace.trace_id}", timeout=10).read())
            _assert_well_formed(one)
            names = {s["name"].split("@")[0] for s in one["spans"]}
            assert {"queued", "prefill", "decode"} <= names
            assert {s["replica"] for s in one["spans"]
                    if s["name"].startswith("attempt@")} == {"a", "b"}
        finally:
            srv.stop()

    def test_untraced_gateway_stays_zero_cost(self, model_and_params):
        """tracer=None: no TraceContext is minted, engines get
        trace_ctx=None, nothing binds — the off path is one attribute
        check, same as before."""
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock())
        gw.add_replica(_paged(model, params, tracer=None), "a")
        r = gw.submit([5, 17, 3], 4)
        gw.run_to_completion(max_ticks=200)
        assert r.status == "finished" and r.trace is None


# ------------------------------------------------------------ chrome flow --

class TestChromeFlowEvents:
    def test_dispatch_and_admit_emit_matching_flow_pair(
            self, model_and_params):
        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock(), tracer=Tracer())
        gw.add_replica(_paged(model, params), "a")
        r = gw.submit([5, 17, 3], 4)
        gw.run_to_completion(max_ticks=200)
        gw_chrome = events_to_chrome(gw.tracer.events())
        eng_chrome = events_to_chrome(
            gw.replica("a").engine.tracer.events())
        starts = [e for e in gw_chrome["traceEvents"] if e.get("ph") == "s"]
        finishes = [e for e in eng_chrome["traceEvents"]
                    if e.get("ph") == "f"]
        assert starts and finishes
        assert starts[0]["id"] == finishes[0]["id"]     # same attempt span
        assert starts[0]["args"]["trace_id"] == r.trace.trace_id
        assert finishes[0]["bp"] == "e"

    def test_trace_to_chrome_multi_engine_merge(self, tmp_path,
                                                model_and_params):
        """tools/trace_to_chrome.py: repeated --engine-trace dumps merge
        with per-file pid suffixes (replica rid rows must not collide)
        while flow ids survive untouched."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "_t2c", "tools/trace_to_chrome.py")
        t2c = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(t2c)

        model, params = model_and_params
        gw = ServingGateway(clock=FakeClock(), tracer=Tracer())
        gw.add_replica(_paged(model, params), "a")
        gw.add_replica(_paged(model, params), "b")
        for p, n in (([5, 17, 3], 4), ([40, 2], 3), ([61], 3)):
            gw.submit(p, n)
        gw.run_to_completion(max_ticks=300)

        paths = []
        for i, tr in enumerate([gw.tracer,
                                gw.replica("a").engine.tracer,
                                gw.replica("b").engine.tracer]):
            p = tmp_path / f"dump{i}.jsonl"
            tr.dump_jsonl(str(p))
            paths.append(str(p))
        merged = {"traceEvents": []}
        for i, p in enumerate(paths):
            trace = t2c._suffix_pids(t2c._load_engine_trace(p), i)
            merged["traceEvents"].extend(trace["traceEvents"])
        pids = {e["pid"] for e in merged["traceEvents"]}
        assert {"paddle_tpu.serving#0", "paddle_tpu.serving#1",
                "paddle_tpu.serving#2"} <= pids
        starts = {e["id"] for e in merged["traceEvents"]
                  if e.get("ph") == "s"}
        finishes = {e["id"] for e in merged["traceEvents"]
                    if e.get("ph") == "f"}
        assert starts and starts == finishes     # every arrow lands


# ----------------------------------------------------------- mfu / costs --

class TestCostAttribution:
    def test_engine_compile_seam_records_flops_and_mfu(self):
        # a FRESH model: the compile-event flops assertion below needs
        # real program-cache misses, not hits against the module model
        paddle.seed(11)
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=96,
                        compute_dtype="float32")
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        tr = Tracer(attribute_cost=True, peak_flops=1e12)
        eng = _paged(model, params, tracer=tr)
        eng.add_request([5, 17, 3], 4)
        eng.run_to_completion(max_ticks=100)
        assert any(e.get("flops") for e in tr.events("compile"))
        mfu = tr.summary()["mfu"]
        assert mfu["model_flops_total"] > 0
        assert mfu["model_flops_per_s"] > 0
        assert mfu["arithmetic_intensity"] > 0
        assert 0 < mfu["mfu"] < 1
        assert any(e.get("flops") for e in tr.events("tick"))
        text = tr.prometheus_text()
        assert "paddle_tpu_serving_model_flops_total" in text
        assert "paddle_tpu_serving_mfu" in text

    def test_cost_off_by_default(self, model_and_params):
        model, params = model_and_params
        tr = Tracer()
        # fresh model so the program cache is cold
        paddle.seed(11)
        cfg = model.config
        m2 = GPTModel(cfg)
        p2 = {n: p._data for n, p in m2.named_parameters()}
        eng = PagedContinuousBatchingEngine(
            m2, p2, max_slots=2, max_len=32, block_size=4,
            prompt_buckets=[8, 16], tracer=tr)
        eng.add_request([5, 17, 3], 4)
        eng.run_to_completion(max_ticks=100)
        assert tr.summary()["mfu"]["model_flops_total"] == 0.0
        assert tr.summary()["mfu"]["mfu"] is None

    def test_compile_aot_attaches_cost_for_free(self):
        from paddle_tpu.jit.aot import compile_aot
        from paddle_tpu.telemetry import TrainMonitor
        mon = TrainMonitor(peak_flops=1e12)

        def f(x):
            return x @ x

        compiled, prov = compile_aot(
            f, [jnp.ones((16, 16), jnp.float32)], monitor=mon,
            label="mm")
        assert prov == "cold"
        ev = mon.events("compile")[-1]
        assert ev.get("flops", 0) > 0
        mon.record_step(0.01, trainer="t", examples=1)
        mon.record_step(0.01, trainer="t", examples=1)
        mfu = mon.summary()["mfu"]
        assert mfu["model_flops_per_step"] > 0
        assert mfu["model_flops_per_s"] > 0 and mfu["mfu"] > 0


# ------------------------------------------------------- off-path purity --

class TestOffPathPurity:
    def test_lowerings_byte_identical_with_tracing_and_trace_ctx(self):
        """The PR 4 purity pin extended to trace-context plumbing: an
        engine with a tracer + bound TraceContexts lowers byte-identical
        programs to a bare engine — tracing is host-side metadata
        only."""
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_attention_heads=2,
                        max_position_embeddings=64,
                        compute_dtype="float32")

        def build(tracer):
            model = GPTModel(cfg)
            params = {n: p._data for n, p in model.named_parameters()}
            return ContinuousBatchingEngine(
                model, params, max_slots=2, max_len=32,
                prompt_buckets=[8], tracer=tracer)

        def lowered_texts(eng):
            ck, cv = eng._alloc_caches()
            pre = eng._build_prefill(8).lower(
                eng.params, ck, cv, jnp.zeros((1, 8), jnp.int32),
                jnp.int32(0), jnp.int32(0), jax.random.key(0),
                eng._scratch_presence(), eng._plane_operands()).as_text()
            ck, cv = eng._alloc_caches()
            z = jnp.zeros(eng.S, jnp.int32)
            dec = eng._build_decode().lower(
                eng.params, ck, cv, z, z, z,
                jnp.zeros(eng.S, bool), jax.random.key(0),
                eng._scratch_presence(), z,
                eng._plane_operands()).as_text()
            return pre, dec

        on = build(Tracer(attribute_cost=True))
        # exercise the traced path (binds a context) before lowering
        on.add_request([1, 2, 3], 2, trace_ctx=TraceContext.root())
        on.run_to_completion(max_ticks=50)
        off = build(None)
        for a, b in zip(lowered_texts(on), lowered_texts(off)):
            assert a == b

    def test_program_cache_keys_identical_with_and_without_tracing(self):
        """Same engine config, traced vs untraced, on SEPARATE models:
        the model-level program cache keys are identical — a traced
        engine can never fork the compiled-program population."""
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_attention_heads=2,
                        max_position_embeddings=64,
                        compute_dtype="float32")

        def run(tracer, ctx):
            paddle.seed(0)           # identical params per build
            model = GPTModel(cfg)
            params = {n: p._data for n, p in model.named_parameters()}
            eng = ContinuousBatchingEngine(
                model, params, max_slots=2, max_len=32,
                prompt_buckets=[8], tracer=tracer)
            eng.add_request([1, 2, 3], 2, trace_ctx=ctx)
            out = eng.run_to_completion(max_ticks=50)
            return (set(model.__dict__["_serving_programs"]),
                    list(out.values())[0])

        keys_on, toks_on = run(Tracer(), TraceContext.root())
        keys_off, toks_off = run(None, None)
        assert keys_on == keys_off
        assert toks_on == toks_off
