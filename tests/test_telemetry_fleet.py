"""Fleet observability plane (paddle_tpu/telemetry_fleet.py, ISSUE 19):
cross-process telemetry federation, the durable metric spool, and the
fleet rollups.

The acceptance pins run entirely on a fake clock: a collector over >= 3
mixed targets whose rollups match hand-computed merges (global goodput
from summed ledger seconds, fleet TTFT p99 from an independently built
PercentileSketch merge), a killed target flipping to ``stale`` within
the window WITHOUT corrupting the surviving rollups, the spool surviving
a simulated crash with no duplicate and no lost durable samples, and
``GET /fleet`` + ``tools/fleet_top.py`` rendering the SAME snapshot.
The emitter/parser drift guard round-trips every Prometheus emitter
family in the tree through the collector's own parser, and the off-path
purity pin shows engine lowerings are byte-identical with a collector
scraping the process vs. none attached."""

import importlib.util
import json
import os
import pathlib
import urllib.error
import urllib.request

import pytest

from paddle_tpu.autoscaler import ElasticAutoscaler
from paddle_tpu.gateway import ServingGateway
from paddle_tpu.ops_server import OpsServer
from paddle_tpu.simulation import (SimClock, SimEngine, SimFleetHost,
                                   SimTracer, build_sim_fleet)
from paddle_tpu.telemetry_fleet import (FleetCollector, ParsedSample,
                                        TelemetrySpool,
                                        parse_prometheus_text,
                                        render_sample, replay_regressions)
from paddle_tpu.telemetry_ledger import FlightRecorder, RunLedger
from paddle_tpu.telemetry_memory import MemoryLedger
from paddle_tpu.telemetry_slo import (Objective, PercentileSketch,
                                      SLOMonitor)
from paddle_tpu.utils.stats import (StatRegistry, prom_sample,
                                    prometheus_text)

_TOOLS = pathlib.Path(__file__).parent.parent / "tools"


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(name,
                                                  _TOOLS / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def _fetch_target(metrics_text, extra=None):
    """A ``fetch(path)`` transport over canned payloads — the fake-clock
    harness the module docstring names."""
    extra = dict(extra or {})

    def fetch(path):
        if path == "/metrics":
            return metrics_text
        return extra.get(path)

    return fetch


def _ledger_payload(compute_s, elapsed_s):
    return {"goodput": compute_s / elapsed_s, "elapsed_s": elapsed_s,
            "buckets_s": {"compute": compute_s}}


# ---------------------------------------------------------------------------
# the Prometheus parser
# ---------------------------------------------------------------------------

class TestPrometheusParser:
    def test_names_labels_values_and_types(self):
        text = ("# HELP x_total ignored\n"
                "# TYPE x_total counter\n"
                "x_total 3\n"
                'x_bucket{le="0.5",route="a"} 2\n'
                "y_gauge -0.25\n")
        parsed = parse_prometheus_text(text)
        assert parsed["errors"] == []
        assert parsed["types"] == {"x_total": "counter"}
        assert parsed["samples"] == [
            ParsedSample("x_total", {}, 3.0),
            ParsedSample("x_bucket", {"le": "0.5", "route": "a"}, 2.0),
            ParsedSample("y_gauge", {}, -0.25)]

    def test_label_escaping_round_trip(self):
        """The parser is the exact inverse of ``prom_escape_label`` —
        backslashes, quotes, and newlines survive a full round trip."""
        nasty = 'back\\slash "quote"\nnewline'
        line = prom_sample("m", 1.5, {"name": nasty, "plain": "v"})
        parsed = parse_prometheus_text(line)
        assert parsed["errors"] == []
        (s,) = parsed["samples"]
        assert s.labels == {"name": nasty, "plain": "v"}
        assert render_sample(s) == line

    def test_garbage_collected_not_raised(self):
        """One corrupt line must not void the rest of the scrape."""
        text = ("good 1\n"
                "}{ total garbage\n"
                "bad_value{a=\"b\"} not_a_float\n"
                "also_good 2\n")
        parsed = parse_prometheus_text(text)
        assert [s.name for s in parsed["samples"]] == ["good",
                                                       "also_good"]
        assert len(parsed["errors"]) == 2


# ---------------------------------------------------------------------------
# emitter/parser drift guard: every prometheus_text family round-trips
# ---------------------------------------------------------------------------

def _assert_round_trips(text):
    """Every sample line an emitter produced must parse cleanly AND
    re-render byte-identically through the shared ``prom_sample``
    renderer — the no-drift contract between every emitter and the ONE
    parser."""
    parsed = parse_prometheus_text(text)
    assert parsed["errors"] == [], parsed["errors"]
    n_sample_lines = 0
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        n_sample_lines += 1
        one = parse_prometheus_text(line)
        assert len(one["samples"]) == 1, line
        assert render_sample(one["samples"][0]) == line
    assert n_sample_lines == len(parsed["samples"])
    assert n_sample_lines > 0, "emitter produced no samples"


class TestEmitterParserDriftGuard:
    def test_stats_registry_family(self):
        reg = StatRegistry()
        reg.add("requests", 7)
        reg.set("gauge_like", 0.125)
        reg.observe("latency_s", 0.05, bounds=(0.01, 0.1, 1.0))
        reg.observe("latency_s", 5.0)
        _assert_round_trips(prometheus_text(
            reg, namespace="paddle_tpu",
            extra_gauges={"derived": 1.75}))

    def test_serving_tracer_family(self):
        clk = SimClock()
        host = SimFleetHost(clk, name="drift")
        host.submit([1, 2, 3, 4], 4)
        for _ in range(8):
            clk.advance(0.05)
            host.engine.step()
        _assert_round_trips(host.tracer.prometheus_text())
        _assert_round_trips(host.engine.prometheus_text())

    def test_gateway_family(self):
        clk = SimClock()
        gw = ServingGateway(clock=clk, tracer=SimTracer(clk))
        eng = SimEngine(max_slots=2, tracer=SimTracer(clk))
        eng.warmup()
        gw.add_replica(eng, "r0")
        _assert_round_trips(gw.prometheus_text())

    def test_ledger_family(self):
        led = RunLedger()
        led.record("compute", 1.25)
        led.record("data_wait", 0.5)
        _assert_round_trips(led.prometheus_text())

    def test_memory_family(self):
        mem = MemoryLedger()
        mem.account("kv_pages", 1 << 20, space="device")
        mem.account("params", 1 << 18, space="host")
        _assert_round_trips(mem.prometheus_text())

    def test_slo_family(self):
        clk = FakeClock()
        mon = SLOMonitor([
            Objective.latency("ttft_p99", "ttft_s", 0.5),
            Objective.ratio("shed_rate", "shed", "submitted", 0.05),
            Objective.floor("goodput_floor", "goodput", 0.5)],
            clock=clk, resolution_s=1.0)
        for i in range(10):
            mon.observe("ttft_s", 0.1 * i, now=float(i))
            mon.observe("goodput", 0.7, now=float(i))
            mon.count("submitted", now=float(i))
        clk.t = 10.0
        mon.evaluate(10.0)
        _assert_round_trips(mon.prometheus_text())

    def test_autoscaler_family(self):
        clk = SimClock()
        gw = ServingGateway(clock=clk, tracer=SimTracer(clk))
        eng = SimEngine(max_slots=2, tracer=SimTracer(clk))
        eng.warmup()
        gw.add_replica(eng, "r0")
        asc = ElasticAutoscaler(gw, None, min_replicas=1, max_replicas=2,
                                clock=clk)
        asc.evaluate()
        _assert_round_trips(asc.prometheus_text())

    def test_kvstore_family(self):
        np = pytest.importorskip("numpy")
        from paddle_tpu.kv_store import KVPage, TieredKVStore
        st = TieredKVStore(dram_capacity_bytes=1 << 20)
        arr = np.full(64, 3, np.float32)
        st.put(KVPage(b"k" * 32, (arr,), ["t", 1]))
        st.lookup(b"k" * 32)
        st.lookup(b"z" * 32)
        _assert_round_trips(st.prometheus_text())

    def test_fleet_collector_family(self):
        """The federation gauges round-trip through the collector's OWN
        parser — the plane can federate itself one level up."""
        clk = FakeClock()
        col = FleetCollector(interval_s=5.0, clock=clk)
        col.add_target("a", fetch=_fetch_target(
            "a_tokens_emitted 5\n",
            {"/ledger": _ledger_payload(30.0, 100.0)}))
        col.scrape_once()
        _assert_round_trips(col.prometheus_text())


# ---------------------------------------------------------------------------
# fleet rollups: hand-computed merges (the acceptance pins)
# ---------------------------------------------------------------------------

class TestFleetRollups:
    def test_goodput_and_skew_match_hand_computed_merge(self):
        """3 targets with known ledger seconds: global goodput is
        sum(compute)/sum(elapsed) — the RunLedger.aggregate merge
        discipline — and straggler skew is max/mean compute."""
        clk = FakeClock()
        col = FleetCollector(interval_s=5.0, clock=clk)
        seconds = {"h0": (30.0, 100.0), "h1": (60.0, 100.0),
                   "h2": (90.0, 100.0)}
        for name, (c, e) in seconds.items():
            col.add_target(name, fetch=_fetch_target(
                f"{name}_tokens_emitted 0\n",
                {"/ledger": _ledger_payload(c, e)}))
        snap = col.scrape_once()
        roll = snap["rollup"]
        assert roll["targets"] == 3 and roll["targets_ok"] == 3
        assert roll["goodput_global"] == pytest.approx(
            (30.0 + 60.0 + 90.0) / 300.0, rel=1e-12)
        assert roll["straggler_skew"] == pytest.approx(
            90.0 / ((30.0 + 60.0 + 90.0) / 3.0), rel=1e-12)
        by = {r["target"]: r for r in snap["targets"]}
        assert by["h1"]["compute_s"] == 60.0
        assert by["h1"]["elapsed_s"] == 100.0
        assert by["h1"]["goodput"] == pytest.approx(0.6)

    def test_fleet_ttft_p99_matches_hand_built_sketch_merge(self):
        """The merged percentile is a real quantile of the union of
        samples: the collector's number (through serialize → transport →
        reconstruct → merge) equals a PercentileSketch built by hand
        from every raw observation — not an average of per-target
        quantiles."""
        clk = FakeClock()
        samples = {"h0": [0.1, 0.2, 0.3, 3.0],
                   "h1": [0.5, 0.5, 0.5, 0.5, 0.5],
                   "h2": [1.0, 2.0]}
        monitors = {}
        for name, values in samples.items():
            mon = SLOMonitor(clock=clk, resolution_s=5.0)
            for i, v in enumerate(values):
                mon.observe("ttft_s", v, now=0.1 * i)
            monitors[name] = mon
        col = FleetCollector(interval_s=5.0, clock=clk)
        for name, mon in monitors.items():
            col.add_target(name, fetch=_fetch_target(
                f"{name}_tokens_emitted 0\n", {"/slo": mon.snapshot()}))
        roll = col.scrape_once()["rollup"]

        hand = PercentileSketch()
        for values in samples.values():
            per_host = PercentileSketch()
            for v in values:
                per_host.add(v)
            hand.merge(per_host)
        assert roll["fleet_ttft_p99"] == pytest.approx(
            hand.quantile(0.99), rel=1e-12)
        assert roll["fleet_ttft_p50"] == pytest.approx(
            hand.quantile(0.50), rel=1e-12)
        # and the naive wrong merge (mean of per-target p99s) differs —
        # the pin is meaningful
        naive = sum(
            max(vs) for vs in samples.values()) / len(samples)
        assert roll["fleet_ttft_p99"] != pytest.approx(naive, rel=0.01)

    def test_tokens_per_s_from_counter_deltas(self):
        clk = FakeClock()
        box = {"h0": 0.0, "h1": 0.0}

        def make(name):
            def fetch(path):
                if path == "/metrics":
                    return f"{name}_tokens_emitted {box[name]}\n"
                return None
            return fetch

        col = FleetCollector(interval_s=5.0, clock=clk)
        col.add_target("h0", fetch=make("h0"))
        col.add_target("h1", fetch=make("h1"))
        first = col.scrape_once()
        assert first["rollup"]["tokens_per_s"] is None  # no delta yet
        box["h0"], box["h1"] = 50.0, 25.0
        clk.advance(5.0)
        roll = col.scrape_once()["rollup"]
        assert roll["tokens_per_s"] == pytest.approx(75.0 / 5.0)
        # counter reset (target restarted): rate withheld, not negative
        box["h0"] = 3.0
        clk.advance(5.0)
        snap = col.scrape_once()
        by = {r["target"]: r for r in snap["targets"]}
        assert by["h0"]["tokens_per_s"] is None
        assert by["h1"]["tokens_per_s"] == pytest.approx(0.0)

    def test_scalar_rollups_drive_fleet_regression_alert(self):
        """A floor objective on ``goodput_global`` IS the live fleet
        regression detector: sustained low goodput fires through the
        multi-window burn machinery on the collector's own clock."""
        clk = FakeClock()
        col = FleetCollector(
            interval_s=5.0, clock=clk,
            objectives=[Objective.floor(
                "goodput_floor", "goodput_global", 0.5, compliance=0.9,
                windows=(30.0, 10.0), burn_threshold=1.0, for_s=2.0,
                clear_s=10.0)])
        col.add_target("h0", fetch=_fetch_target(
            "h0_tokens_emitted 0\n",
            {"/ledger": _ledger_payload(20.0, 100.0)}))
        fired = False
        for _ in range(20):
            fired = fired or \
                col.scrape_once()["slo"]["alerts_firing"] >= 1
            clk.advance(5.0)
        assert fired


# ---------------------------------------------------------------------------
# staleness: a dead target is a labeled gap, never a silent merge
# ---------------------------------------------------------------------------

class TestStaleness:
    def _mortal_fleet(self, clk):
        """3 targets; h2's transport dies when told to."""
        dead = {"h2": False}
        monitors = {}
        seconds = {"h0": (30.0, 100.0), "h1": (60.0, 100.0),
                   "h2": (90.0, 100.0)}
        ttfts = {"h0": [0.1, 0.2], "h1": [0.3, 0.4], "h2": [5.0, 6.0]}
        col = FleetCollector(interval_s=5.0, clock=clk)  # stale at 15s
        for name, (c, e) in seconds.items():
            mon = SLOMonitor(clock=clk, resolution_s=5.0)
            for i, v in enumerate(ttfts[name]):
                mon.observe("ttft_s", v, now=0.1 * i)
            monitors[name] = mon

            def fetch(path, name=name):
                if dead.get(name):
                    raise OSError(f"{name} unreachable")
                if path == "/metrics":
                    return f"{name}_tokens_emitted 0\n"
                if path == "/ledger":
                    return _ledger_payload(*seconds[name])
                if path == "/slo":
                    return monitors[name].snapshot()
                return None

            col.add_target(name, fetch=fetch)
        return col, dead

    def test_killed_target_flips_stale_without_corrupting_rollups(self):
        clk = FakeClock()
        col, dead = self._mortal_fleet(clk)
        roll = col.scrape_once()["rollup"]
        assert roll["targets_ok"] == 3
        assert roll["goodput_global"] == pytest.approx(180.0 / 300.0)

        dead["h2"] = True
        clk.advance(5.0)
        snap = col.scrape_once()       # failed, but within the window
        by = {r["target"]: r for r in snap["targets"]}
        assert by["h2"]["status"] == "ok"      # last good scrape recent
        assert by["h2"]["consecutive_failures"] == 1
        # past stale_after_s (3 * interval): labeled stale, with its age
        # and last error — and EXCLUDED from every rollup
        clk.advance(15.0)
        snap = col.scrape_once()
        by = {r["target"]: r for r in snap["targets"]}
        assert by["h2"]["status"] == "stale"
        assert by["h2"]["age_s"] > col.stale_after_s
        assert "unreachable" in by["h2"]["error"]
        roll = snap["rollup"]
        assert roll["targets_ok"] == 2 and roll["targets_stale"] == 1
        assert roll["goodput_global"] == pytest.approx(90.0 / 200.0)
        assert roll["straggler_skew"] == pytest.approx(60.0 / 45.0)
        # h2's 5-6s TTFTs must not haunt the merged percentile
        hand = PercentileSketch()
        for v in (0.1, 0.2, 0.3, 0.4):
            hand.add(v)
        assert roll["fleet_ttft_p99"] == pytest.approx(
            hand.quantile(0.99), rel=1e-12)

    def test_never_scraped_is_down_and_backoff_bounds_retries(self):
        clk = FakeClock()
        calls = {"n": 0}

        def fetch(path):
            calls["n"] += 1
            raise OSError("never up")

        col = FleetCollector(interval_s=5.0, clock=clk,
                             backoff_max_s=60.0)
        col.add_target("ghost", fetch=fetch)
        snap = col.scrape_once()
        assert snap["targets"][0]["status"] == "down"
        assert snap["rollup"]["targets_down"] == 1
        n_after_first = calls["n"]
        # consecutive failures back off exponentially: an immediate
        # re-scrape round skips the target entirely
        col.scrape_once()
        assert calls["n"] == n_after_first
        clk.advance(5.0)               # past the first 5s backoff
        col.scrape_once()
        assert calls["n"] == n_after_first + 1

    def test_http_targets_over_real_ops_servers(self):
        """Two STARTED ops servers scraped over real HTTP; stopping one
        flips it to stale while the survivor stays ok."""
        clk = SimClock()
        h0, h1 = SimFleetHost(clk, name="h0"), SimFleetHost(clk, name="h1")
        h0.submit([1, 2, 3], 3)
        for _ in range(6):
            clk.advance(0.05)
            h0.engine.step()
            h1.engine.step()
        fclk = FakeClock()
        col = FleetCollector(interval_s=5.0, clock=fclk, timeout_s=5.0)
        url0, url1 = h0.server.start(), h1.server.start()
        try:
            col.add_target("h0", url0)
            col.add_target("h1", url1)
            roll = col.scrape_once()["rollup"]
            assert roll["targets_ok"] == 2
            h1.server.stop()
            fclk.advance(20.0)         # past stale_after_s
            snap = col.scrape_once()
            by = {r["target"]: r for r in snap["targets"]}
            assert by["h0"]["status"] == "ok"
            assert by["h1"]["status"] == "stale"
            assert by["h1"]["error"] is not None
        finally:
            h0.server.stop()
            h1.server.stop()


# ---------------------------------------------------------------------------
# the durable spool
# ---------------------------------------------------------------------------

class TestTelemetrySpool:
    def test_rotation_and_retention(self, tmp_path):
        sp = TelemetrySpool(str(tmp_path), segment_bytes=1024,
                            max_segments=2)
        pad = "x" * 100
        for i in range(60):
            sp.append({"i": i, "pad": pad})
        sp.close()
        names = sorted(f for f in os.listdir(tmp_path)
                       if f.startswith("spool-"))
        assert len(names) == 2          # retention cap holds
        recs = TelemetrySpool(str(tmp_path), segment_bytes=1024,
                              max_segments=2).records()
        seqs = [r["seq"] for r in recs]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        assert seqs[-1] == 60           # newest records survive

    def test_torn_tail_without_newline_is_truncated(self, tmp_path):
        sp = TelemetrySpool(str(tmp_path))
        for i in range(5):
            sp.append({"i": i})
        sp.close()
        (seg,) = [f for f in os.listdir(tmp_path)
                  if f.startswith("spool-")]
        with open(tmp_path / seg, "a") as f:
            f.write('{"i": 5, "seq": 6')      # crash mid-write
        sp2 = TelemetrySpool(str(tmp_path))
        recs = sp2.records()
        assert [r["i"] for r in recs] == [0, 1, 2, 3, 4]
        assert sp2.append({"i": "post"}) == 6  # seq resumes, no gap
        assert [r["seq"] for r in sp2.records()] == [1, 2, 3, 4, 5, 6]

    def test_torn_tail_with_newline_is_truncated(self, tmp_path):
        """A torn write that DID land its newline is still unparseable
        JSON — dropped the same way."""
        sp = TelemetrySpool(str(tmp_path))
        for i in range(3):
            sp.append({"i": i})
        sp.close()
        (seg,) = [f for f in os.listdir(tmp_path)
                  if f.startswith("spool-")]
        with open(tmp_path / seg, "a") as f:
            f.write('{"i": 3, "se\n')
        sp2 = TelemetrySpool(str(tmp_path))
        assert [r["i"] for r in sp2.records()] == [0, 1, 2]
        assert sp2.append({"i": 3}) == 4

    def test_validation(self, tmp_path):
        with pytest.raises(ValueError):
            TelemetrySpool(str(tmp_path), segment_bytes=10)
        with pytest.raises(ValueError):
            TelemetrySpool(str(tmp_path), max_segments=1)

    def test_collector_spool_survives_simulated_crash(self, tmp_path):
        """The end-to-end crash pin: scrape → kill the process mid-write
        (emulated by a torn tail) → a NEW collector resumes the spool
        with no duplicate and no lost durable samples."""
        clk = FakeClock()
        spool_dir = str(tmp_path / "spool")

        def build():
            c = FleetCollector(interval_s=5.0, clock=clk,
                               spool_dir=spool_dir)
            c.add_target("h0", fetch=_fetch_target(
                "h0_tokens_emitted 0\n",
                {"/ledger": _ledger_payload(30.0, 100.0)}))
            return c

        col = build()
        col.scrape_once()
        clk.advance(5.0)
        col.scrape_once()
        before = col.spool.records()
        col.stop()                     # closes the spool
        # crash: a torn half-record at the tail of the open segment
        segs = sorted(f for f in os.listdir(spool_dir)
                      if f.startswith("spool-"))
        with open(os.path.join(spool_dir, segs[-1]), "a") as f:
            f.write('{"kind": "rollup", "ts": 99')
        col2 = build()
        assert col2.spool.records() == before   # nothing durable lost
        clk.advance(5.0)
        col2.scrape_once()
        seqs = [r["seq"] for r in col2.spool.records()]
        assert seqs == list(range(1, len(seqs) + 1))  # no dup, no gap
        # per-scrape shape: one target row + one rollup per round
        kinds = [r["kind"] for r in col2.spool.records()]
        assert kinds == ["target", "rollup"] * 3


# ---------------------------------------------------------------------------
# surfaces: GET /fleet, fleet_top, federation gauges, FlightRecorder
# ---------------------------------------------------------------------------

class TestFleetSurfaces:
    def _collector(self, clk):
        col = FleetCollector(interval_s=5.0, clock=clk)
        mon = SLOMonitor(clock=clk, resolution_s=5.0)
        for v in (0.1, 0.4, 0.9):
            mon.observe("ttft_s", v, now=0.1)
        col.add_target("h0", fetch=_fetch_target(
            "h0_tokens_emitted 4\n",
            {"/ledger": _ledger_payload(30.0, 100.0),
             "/slo": mon.snapshot()}))
        return col

    def test_fleet_route_and_dashboard_render_same_snapshot(self):
        """GET /fleet over real HTTP serves the same object
        ``fleet_snapshot()`` returns, and fleet_top renders identical
        frames from either — one snapshot, every surface."""
        fleet_top = _load_tool("fleet_top")
        clk = FakeClock()
        col = self._collector(clk)
        col.scrape_once()
        srv = OpsServer()
        srv.attach(col, "fleet")
        url = srv.start()
        try:
            via_http = json.loads(urllib.request.urlopen(
                url + "/fleet", timeout=10).read())
        finally:
            srv.stop()
        local = col.fleet_snapshot()
        assert via_http == json.loads(json.dumps(local))
        frame_http = fleet_top.render_fleet(via_http)
        frame_local = fleet_top.render_fleet(local)
        assert frame_http == frame_local
        assert "h0" in frame_local and "ok" in frame_local

    def test_fleet_route_404_without_collector(self):
        srv = OpsServer()
        srv.attach(SLOMonitor(), "slo")
        url = srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url + "/fleet", timeout=10)
            assert ei.value.code == 404
        finally:
            srv.stop()

    def test_dashboard_marks_stale_targets_visible(self):
        fleet_top = _load_tool("fleet_top")
        clk = FakeClock()
        boom = {"on": False}

        def fetch(path):
            if boom["on"]:
                raise OSError("scrape refused")
            return "h_tokens_emitted 0\n" if path == "/metrics" else None

        col = FleetCollector(interval_s=5.0, clock=clk)
        col.add_target("mortal", fetch=fetch)
        col.scrape_once()
        boom["on"] = True
        clk.advance(20.0)
        frame = fleet_top.render_fleet(col.scrape_once())
        assert "stale" in frame
        assert "scrape refused" in frame   # the labeled gap, visible

    def test_prerender_snapshot_shape_before_first_scrape(self):
        col = FleetCollector(interval_s=5.0, clock=FakeClock())
        col.add_target("h0", fetch=_fetch_target("x_tokens_emitted 0\n"))
        snap = col.fleet_snapshot()
        assert snap["targets"] == [] and snap["scrapes"] == 0
        assert snap["rollup"]["targets_down"] == 1

    def test_flight_recorder_dumps_fleet_json(self, tmp_path):
        clk = FakeClock()
        col = FleetCollector(interval_s=5.0, clock=clk,
                             spool_dir=str(tmp_path / "spool"))
        col.add_target("h0", fetch=_fetch_target(
            "h0_tokens_emitted 0\n",
            {"/ledger": _ledger_payload(30.0, 100.0)}))
        col.scrape_once()
        fr = FlightRecorder(str(tmp_path / "crash"))
        fr.add_source(col, "fleet")
        out_dir = fr.dump("test")
        assert out_dir is not None
        payload = json.loads(
            (pathlib.Path(out_dir) / "fleet.json").read_text())
        assert payload["snapshot"]["rollup"]["targets_ok"] == 1
        assert payload["spool_tail"][-1]["kind"] == "rollup"


# ---------------------------------------------------------------------------
# the sim fleet: whole federation pipeline on one fake clock
# ---------------------------------------------------------------------------

class TestSimFleet:
    def test_three_host_pipeline_end_to_end(self, tmp_path):
        clk = SimClock()
        col, hosts = build_sim_fleet(clk, 3, interval_s=5.0,
                                     spool_dir=str(tmp_path))
        for host in hosts:
            host.submit([1, 2, 3, 4], 6)
        for _ in range(40):
            clk.advance(0.05)
            for host in hosts:
                host.engine.step()
                host.ledger.record("compute", 0.05)
        col.scrape_once()
        clk.advance(5.0)
        snap = col.scrape_once()
        roll = snap["rollup"]
        assert roll["targets_ok"] == 3
        assert roll["fleet_ttft_p99"] is not None
        assert [r["status"] for r in snap["targets"]] == ["ok"] * 3
        # second scrape has token deltas (all emitted in window 1 → 0/s
        # now is legitimate; the field must be present, not None)
        assert roll["tokens_per_s"] is not None
        assert snap["spool"]["seq"] == 8    # (3 targets + 1 rollup) * 2

    def test_build_sim_fleet_validates(self):
        with pytest.raises(ValueError):
            build_sim_fleet(SimClock(), 0)


# ---------------------------------------------------------------------------
# collector as an autoscaler signal
# ---------------------------------------------------------------------------

class _StubFleet:
    def __init__(self, p99):
        self.p99 = p99

    def fleet_snapshot(self):
        return {"rollup": {"fleet_ttft_p99": self.p99}}


class TestAutoscalerFleetSignal:
    def _gw(self, clk, replicas=1):
        gw = ServingGateway(clock=clk, tracer=SimTracer(clk))
        for i in range(replicas):
            eng = SimEngine(max_slots=2, tracer=SimTracer(clk))
            eng.warmup()
            gw.add_replica(eng, f"r{i}")
        return gw

    def test_hot_fleet_ttft_triggers_scale_up(self):
        clk = SimClock()
        gw = self._gw(clk)
        spawned = []

        def factory():
            eng = SimEngine(max_slots=2, tracer=SimTracer(clk))
            spawned.append(eng)
            return eng

        asc = ElasticAutoscaler(gw, factory, min_replicas=1,
                                max_replicas=3, clock=clk,
                                fleet=_StubFleet(1.2),
                                fleet_ttft_high=0.5)
        made = asc.evaluate()
        assert [d["action"] for d in made] == ["scale_up"]
        assert "fleet_ttft:1.200" in made[0]["reason"]
        snap = asc.autoscaler_snapshot()
        assert snap["signals"]["fleet_ttft_p99"] == 1.2
        assert snap["signals"]["fleet_ttft_high"] == 0.5

    def test_cool_fleet_ttft_does_not_trigger(self):
        clk = SimClock()
        gw = self._gw(clk)
        asc = ElasticAutoscaler(gw, None, min_replicas=1, max_replicas=3,
                                clock=clk, fleet=_StubFleet(0.1),
                                fleet_ttft_high=0.5)
        assert asc.evaluate() == []

    def test_broken_fleet_poll_never_takes_controller_down(self):
        clk = SimClock()

        class Broken:
            def fleet_snapshot(self):
                raise RuntimeError("collector died")

        asc = ElasticAutoscaler(self._gw(clk), None, min_replicas=1,
                                max_replicas=3, clock=clk, fleet=Broken(),
                                fleet_ttft_high=0.5)
        assert asc.fleet_ttft_p99() is None
        assert asc.evaluate() == []

    def test_ctor_validation(self):
        clk = SimClock()
        with pytest.raises(TypeError):
            ElasticAutoscaler(self._gw(clk), None, fleet=object())
        with pytest.raises(ValueError):
            ElasticAutoscaler(self._gw(clk), None,
                              fleet=_StubFleet(1.0), fleet_ttft_high=0.0)


# ---------------------------------------------------------------------------
# offline regression detection + bench_diff fleet fields
# ---------------------------------------------------------------------------

class TestReplayRegressions:
    def test_throughput_drop_fires_floor_objective(self):
        records = []
        for i in range(24):
            ts = 5.0 * i
            rate = 100.0 if i < 6 else 5.0    # the regression
            records.append({"kind": "rollup", "ts": ts,
                            "tokens_per_s": rate, "seq": i + 1})
            records.append({"kind": "target", "ts": ts,
                            "target": "h0", "seq": 1000 + i})
        snap = replay_regressions(
            records,
            [Objective.floor("tokens_floor", "tokens_per_s", 50.0,
                             compliance=0.9, windows=(30.0, 10.0),
                             burn_threshold=1.0, for_s=2.0,
                             clear_s=10.0)],
            resolution_s=5.0)
        assert snap["replayed_records"] == 24   # target rows ignored
        fired = [t for t in snap.get("transitions", [])
                 if t.get("what") == "firing"
                 and t.get("objective") == "tokens_floor"]
        assert fired, snap

    def test_empty_records(self):
        snap = replay_regressions(
            [], [Objective.floor("f", "tokens_per_s", 1.0)])
        assert snap["replayed_records"] == 0


class TestBenchDiffFleetFields:
    def _rec(self, **fleet):
        return {"metric": "gpt_gateway_ttft_ms_p99", "value": 28.0,
                "unit": "ms", "backend": "cpu", "fleet": fleet}

    def test_fleet_block_expands_to_direction_aware_rows(self):
        bd = _load_tool("bench_diff")
        rows = bd.expand_telemetry([self._rec(
            goodput_global=0.6, fleet_ttft_p99=0.02, straggler_skew=1.5,
            targets=3)])
        by = {r["metric"]: r for r in rows}
        gp = by["gpt_gateway_ttft_ms_p99.fleet.goodput_global"]
        assert gp["direction"] == "higher" and gp["unit"] == "frac"
        assert gp["backend"] == "cpu"
        ttft = by["gpt_gateway_ttft_ms_p99.fleet.fleet_ttft_p99"]
        assert ttft["direction"] == "lower"
        # target counts are scenario context, never judged
        assert "gpt_gateway_ttft_ms_p99.fleet.targets" not in by

    def test_fleet_regression_is_flagged(self):
        bd = _load_tool("bench_diff")
        old = bd.expand_telemetry([self._rec(fleet_ttft_p99=0.02,
                                             goodput_global=0.6)])
        new = bd.expand_telemetry([self._rec(fleet_ttft_p99=0.05,
                                             goodput_global=0.3)])
        rows, n_reg, n_cmp = bd.compare(old, new, threshold=0.1)
        flagged = {r["metric"] for r in rows
                   if str(r["status"]).startswith("REGRESSION")}
        assert "gpt_gateway_ttft_ms_p99.fleet.fleet_ttft_p99" in flagged
        assert "gpt_gateway_ttft_ms_p99.fleet.goodput_global" in flagged
        assert n_reg >= 2 and n_cmp >= 3


# ---------------------------------------------------------------------------
# off-path purity: the collector is a pure pull reader
# ---------------------------------------------------------------------------

class TestOffPathPurity:
    def test_lowerings_byte_identical_with_collector_scraping(self):
        """The PR 2 pin extended to the federation plane: an engine whose
        ops server a FleetCollector actively scrapes lowers byte-
        identical programs to a bare engine — the collector reads
        surfaces that already existed and touches nothing on-device."""
        import jax
        import jax.numpy as jnp
        import paddle_tpu as paddle
        from paddle_tpu.models.gpt import GPTConfig, GPTModel
        from paddle_tpu.serving import ContinuousBatchingEngine

        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_attention_heads=2,
                        max_position_embeddings=64,
                        compute_dtype="float32")

        def build():
            paddle.seed(0)
            model = GPTModel(cfg)
            params = {n: p._data for n, p in model.named_parameters()}
            return ContinuousBatchingEngine(
                model, params, max_slots=2, max_len=32,
                prompt_buckets=[8])

        def lowered_texts(eng):
            ck, cv = eng._alloc_caches()
            pre = eng._build_prefill(8).lower(
                eng.params, ck, cv, jnp.zeros((1, 8), jnp.int32),
                jnp.int32(0), jnp.int32(0), jax.random.key(0),
                eng._scratch_presence(), eng._plane_operands()).as_text()
            ck, cv = eng._alloc_caches()
            z = jnp.zeros(eng.S, jnp.int32)
            dec = eng._build_decode().lower(
                eng.params, ck, cv, z, z, z,
                jnp.zeros(eng.S, bool), jax.random.key(0),
                eng._scratch_presence(), z,
                eng._plane_operands()).as_text()
            return pre, dec

        scraped = build()
        srv = OpsServer()
        srv.attach(scraped)
        col = FleetCollector(interval_s=5.0, clock=FakeClock())
        col.add_target("local", server=srv)
        col.scrape_once()              # actively federated
        bare = build()
        for a, b in zip(lowered_texts(scraped), lowered_texts(bare)):
            assert a == b
