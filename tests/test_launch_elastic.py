"""Launcher + elastic manager tests (reference: launch_utils watch loop and
fleet/elastic/manager.py heartbeat/membership semantics)."""

import os
import subprocess
import sys
import textwrap
import time

import pytest

from paddle_tpu.distributed.fleet.elastic import (ELASTIC_EXIT_CODE,
                                                  RESCALE_EXIT_CODE,
                                                  ElasticManager)


class TestElasticManager:
    def test_heartbeat_and_membership(self, tmp_path):
        m0 = ElasticManager(str(tmp_path), rank=0, heartbeat_interval=0.1,
                            lease_ttl=1.0).register()
        m1 = ElasticManager(str(tmp_path), rank=1, heartbeat_interval=0.1,
                            lease_ttl=1.0).register()
        assert m0.alive_ranks() == [0, 1]
        assert m0.exit_code() is None  # steady state
        m1.stop()
        time.sleep(0.2)
        assert m0.alive_ranks() == [0]
        # fault-tolerance level: peer loss → restart code
        assert m0.exit_code() == ELASTIC_EXIT_CODE
        m0.stop()

    def test_rescale_code_in_elastic_mode(self, tmp_path):
        m0 = ElasticManager(str(tmp_path), rank=0, np_range="1:4",
                            heartbeat_interval=0.1, lease_ttl=5.0).register()
        assert m0.exit_code() is None
        # a new host joins → world grew → rescale
        m2 = ElasticManager(str(tmp_path), rank=2, np_range="1:4",
                            heartbeat_interval=0.1, lease_ttl=5.0).register()
        assert m0.exit_code() == RESCALE_EXIT_CODE
        m0.stop(); m2.stop()

    def test_lease_expiry(self, tmp_path):
        m = ElasticManager(str(tmp_path), rank=0, heartbeat_interval=10,
                           lease_ttl=0.2)
        m._beat()
        assert m.alive_ranks() == [0]
        time.sleep(0.3)
        assert m.alive_ranks() == []


@pytest.mark.skipif(os.environ.get("PADDLE_TPU_SKIP_SUBPROC") == "1",
                    reason="subprocess tests disabled")
class TestLauncher:
    def _run_launch(self, tmp_path, script_body, extra=(), timeout=120):
        script = tmp_path / "train.py"
        script.write_text(textwrap.dedent(script_body))
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        return subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--log_dir", str(tmp_path / "log"), *extra, str(script)],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd="/root/repo")

    def test_single_proc_env_contract(self, tmp_path):
        r = self._run_launch(tmp_path, """
            import os
            assert os.environ["PADDLE_TRAINER_ID"] == "0"
            assert os.environ["PADDLE_TRAINERS_NUM"] == "1"
            print("ENV_OK")
        """)
        assert r.returncode == 0 and "ENV_OK" in r.stdout, r.stderr

    def test_failure_propagates(self, tmp_path):
        r = self._run_launch(tmp_path, "import sys; sys.exit(7)")
        assert r.returncode == 7

    def test_elastic_restart_then_success(self, tmp_path):
        # first run exits 101 (elastic restart), relaunch succeeds
        r = self._run_launch(tmp_path, """
            import os, sys
            flag = os.path.join(os.path.dirname(__file__), "ran_once")
            if not os.path.exists(flag):
                open(flag, "w").close()
                sys.exit(101)
            print("RESUMED")
        """, extra=["--max_restarts", "2"])
        assert r.returncode == 0 and "RESUMED" in r.stdout, r.stderr

    def test_fault_injection_sigkill_restarts_at_level1(self, tmp_path):
        """Fault-tolerant level 1 (reference elastic manager.py:178): a
        trainer killed with SIGKILL (rc=-9, no exit-code protocol possible)
        restarts the pod; the relaunched run succeeds."""
        r = self._run_launch(tmp_path, """
            import os, signal
            flag = os.path.join(os.path.dirname(__file__), "killed_once")
            if not os.path.exists(flag):
                open(flag, "w").close()
                os.kill(os.getpid(), signal.SIGKILL)
            print("SURVIVED")
        """, extra=["--elastic_level", "1", "--max_restarts", "2"])
        assert r.returncode == 0 and "SURVIVED" in r.stdout, (r.stdout, r.stderr)

    def test_sigkill_without_level1_fails(self, tmp_path):
        r = self._run_launch(tmp_path, """
            import os, signal
            os.kill(os.getpid(), signal.SIGKILL)
        """)
        assert r.returncode != 0

    def test_level1_crash_loop_propagates_real_code(self, tmp_path):
        r = self._run_launch(tmp_path, "import sys; sys.exit(7)",
                             extra=["--elastic_level", "1",
                                    "--max_restarts", "2"])
        assert r.returncode == 7, r.returncode  # not 101


class TestTCPStoreLaunch:
    def test_launcher_hosts_tcp_store_end_to_end(self, tmp_path):
        """--elastic_store tcp://127.0.0.1:PORT: the launcher binds the
        native store server in-process and the trainer registers + reads
        membership through it (the no-etcd multi-host path, ≙ reference
        manager.py etcd flows)."""
        import socket
        import subprocess
        import sys
        import textwrap

        with socket.socket() as s:  # reserve a free port number
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        script = tmp_path / "train.py"
        script.write_text(textwrap.dedent(f"""
            from paddle_tpu.distributed.fleet.elastic import ElasticManager
            m = ElasticManager("tcp://127.0.0.1:{port}", rank=0,
                               heartbeat_interval=0.1, lease_ttl=5.0)
            m.register()
            assert m.alive_ranks() == [0], m.alive_ranks()
            m.stop()
            print("TCP_STORE_OK")
        """))
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--log_dir", str(tmp_path / "log"),
             "--elastic_store", f"tcp://127.0.0.1:{port}", str(script)],
            capture_output=True, text=True, timeout=120, env=env,
            cwd="/root/repo")
        assert r.returncode == 0 and "TCP_STORE_OK" in r.stdout, \
            (r.stdout, r.stderr)
