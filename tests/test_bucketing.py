"""Shape bucketing (jit/bucketing.py) — the TPU-native replacement for the
reference's LoD/variable-length handling (fluid/lod_tensor.py): bounded
compile counts, correct padding/masking, output unpadding."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.jit import bucketize, length_mask, pad_to_bucket


class TestBucketize:
    def test_bounded_compiles_across_lengths(self):
        traces = []

        def fn(x):
            traces.append(x.shape)          # runs once per compile (trace)
            return x * 2.0

        f = bucketize(fn, buckets=(8, 16), axis=1)
        for L in (3, 5, 8, 11, 16, 2, 13):
            out = f(jnp.ones((2, L)))
            assert out.shape == (2, L)      # unpadded back
        # 7 calls, only 2 distinct programs ever compiled
        assert sorted(set(traces)) == [(2, 8), (2, 16)]
        assert len(traces) == 2

    def test_values_and_padding(self):
        def fn(x):
            return x + 1.0

        f = bucketize(fn, buckets=(4,), axis=1, pad_value=7.0)
        x = jnp.asarray([[1.0, 2.0]])
        np.testing.assert_allclose(np.asarray(f(x)), [[2.0, 3.0]])

    def test_length_arg_masked_mean(self):
        """The true length rides in as a traced scalar: a masked mean over
        real tokens is exact for every length in the same bucket, with one
        compile."""
        traces = []

        def fn(x, length=None):
            traces.append(())
            m = length_mask(length, x.shape[1], x.dtype)
            return jnp.sum(x * m[None, :], axis=1) / length.astype(x.dtype)

        f = bucketize(fn, buckets=(8,), axis=1, length_arg="length")
        for L in (2, 5, 8):
            x = jnp.ones((3, L)) * 4.0
            np.testing.assert_allclose(np.asarray(f(x)), np.full((3,), 4.0),
                                       rtol=1e-6)
        assert len(traces) == 1             # lengths vary, no recompile

    def test_multiple_args_padded_together(self):
        def fn(x, y):
            return x * y

        f = bucketize(fn, buckets=(6,), axis=1)
        x = jnp.ones((2, 3))
        y = jnp.full((2, 3), 5.0)
        out = f(x, y)
        assert out.shape == (2, 3)
        np.testing.assert_allclose(np.asarray(out), np.full((2, 3), 5.0))

    def test_scalar_args_pass_through(self):
        def fn(x, scale):
            return x * scale

        f = bucketize(fn, buckets=(4,), axis=1)
        out = f(jnp.ones((1, 2)), 3.0)
        np.testing.assert_allclose(np.asarray(out), [[3.0, 3.0]])

    def test_too_long_raises(self):
        f = bucketize(lambda x: x, buckets=(4, 8), axis=1)
        with pytest.raises(ValueError, match="exceeds the largest bucket"):
            f(jnp.ones((1, 9)))

    def test_pad_to_bucket_noop_and_pad(self):
        x = jnp.ones((2, 4))
        assert pad_to_bucket(x, 4, 1) is x
        p = pad_to_bucket(x, 6, 1, pad_value=-1.0)
        assert p.shape == (2, 6)
        np.testing.assert_allclose(np.asarray(p[:, 4:]), -1.0)

    def test_model_end_to_end(self):
        """A tiny attention-free model served at many lengths through two
        buckets — outputs match the unbucketed reference run per length."""
        rs = np.random.RandomState(0)
        W = jnp.asarray(rs.randn(16, 16), jnp.float32)

        def model(x):
            return jnp.tanh(x @ W)

        f = bucketize(model, buckets=(8, 32), axis=1)
        for L in (1, 7, 20, 32):
            x = jnp.asarray(rs.randn(2, L, 16), jnp.float32)
            np.testing.assert_allclose(np.asarray(f(x)),
                                       np.asarray(model(x)),
                                       rtol=1e-6, atol=1e-6)
