"""Quantized gradient collectives (distributed/grad_comm.py).

Oracles:
- block quantize/dequant round trip: constant blocks recover to ~1 ulp
  (the max element hits exactly +-127), adversarial blocks stay inside the
  DOCUMENTED bound |err| <= max|block| / 254 elementwise.
- shard_map parity: ``int8_ef`` all-reduce of a REAL grad pytree matches
  the ``fp32`` mean within the composed two-stage bound max|block| / 127,
  with identical results on every replica; reduce_scatter shards gather
  back to the all_reduce result.
- error feedback: the residual equals exactly v - dequant(sent), and over
  repeated exchanges of a constant gradient the ACCUMULATED applied mean
  tracks the true sum (the EF guarantee: quantization error does not
  accumulate as bias).
- trainers: zero stage-2 / functional / localsgd threading — state grows
  the ``comm_e`` leaf only for stateful policies, losses track fp32, and
  the TrainMonitor ``comm`` accounting reports the >=3.5x int8 savings.
- tiny-GPT convergence smoke (slow): quantized loss curve within
  tolerance of fp32 over ~30 steps.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import grad_comm as gc
from paddle_tpu.distributed.spmd import shard_map

needs4 = pytest.mark.skipif(jax.device_count() < 4, reason="needs 4 devices")


# --------------------------------------------------------------------------
# quantize / dequantize properties
# --------------------------------------------------------------------------

class TestQuantizeBlocks:
    def test_constant_blocks_near_exact(self):
        """A constant block quantizes its every element to +-127, so the
        round trip is exact up to one fp32 rounding of scale*127."""
        for c in (0.1, -3.7, 1e-6, 2.0 ** 20):
            x = jnp.full((4, 256), c, jnp.float32)
            q, s = gc.quantize_blocks(x, 256)
            np.testing.assert_array_equal(
                np.asarray(q), np.full((4, 256), np.sign(c) * 127))
            deq = gc.dequantize_blocks(q, s, 256)
            np.testing.assert_allclose(np.asarray(deq), np.asarray(x),
                                       rtol=1e-6)

    def test_zero_blocks_exact(self):
        x = jnp.zeros((2, 512), jnp.float32)
        q, s = gc.quantize_blocks(x, 256)
        np.testing.assert_array_equal(np.asarray(q), 0)
        np.testing.assert_array_equal(np.asarray(s), 1.0)  # documented
        np.testing.assert_array_equal(
            np.asarray(gc.dequantize_blocks(q, s, 256)), 0.0)

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_documented_elementwise_bound(self, seed):
        """|deq - x| <= max|block| / 254 per element — including the
        adversarial one-outlier-per-block case where the rest of the
        block quantizes to 0."""
        r = np.random.RandomState(seed)
        x = r.standard_normal((8, 256)).astype(np.float32)
        # adversarial: one 1000x outlier per block
        x[:, 7] *= 1000.0
        q, s = gc.quantize_blocks(jnp.asarray(x), 256)
        deq = np.asarray(gc.dequantize_blocks(q, s, 256))
        bound = np.abs(x).max(axis=1, keepdims=True) / 254.0
        assert (np.abs(deq - x) <= bound + 1e-7 * np.abs(x)).all(), \
            np.abs(deq - x).max()

    def test_rejects_ragged_blocks(self):
        with pytest.raises(ValueError, match="multiple"):
            gc.quantize_blocks(jnp.zeros((5,)), 256)


# --------------------------------------------------------------------------
# policy resolution / byte accounting
# --------------------------------------------------------------------------

class TestPolicySurface:
    def test_resolve(self):
        assert gc.resolve_policy(None).name == "fp32"
        assert gc.resolve_policy("bf16").name == "bf16"
        p = gc.Int8EfPolicy(block=128)
        assert gc.resolve_policy(p) is p
        with pytest.raises(ValueError, match="unknown grad_comm"):
            gc.resolve_policy("fp8")
        with pytest.raises(TypeError):
            gc.resolve_policy(3)

    def test_wire_bytes_model(self):
        """The logical ring model: fp32 8N, bf16 4N, int8 2(N + 4N/block)
        — the int8 savings clears the 3.5x contract at the default block
        regardless of tree size."""
        tree = {"w": jnp.zeros((1024, 64)), "b": jnp.zeros((64,))}
        n = 1024 * 64 + 64
        assert gc.wire_bytes(tree, "fp32")["post_bytes"] == 8 * n
        assert gc.wire_bytes(tree, "bf16")["post_bytes"] == 4 * n
        q = gc.wire_bytes(tree, "int8_ef")
        assert q["post_bytes"] == 2 * (n + 4 * (-(-n // 256)))
        assert q["pre_bytes"] / q["post_bytes"] >= 3.5

    def test_comm_info_fp32_is_none(self):
        tree = {"w": jnp.zeros((8, 8))}
        assert gc.comm_info(tree, "fp32") is None
        info = gc.comm_info(tree, "int8_ef")
        assert info["policy"] == "int8_ef"
        assert info["pre_bytes"] > info["post_bytes"]


# --------------------------------------------------------------------------
# error-feedback primitives (shared with dgc.py)
# --------------------------------------------------------------------------

class TestErrorFeedback:
    def test_accumulate_and_residual(self):
        v = gc.ef_accumulate(jnp.asarray([1.0, 2.0]), jnp.asarray([0.5, -1.0]))
        np.testing.assert_array_equal(np.asarray(v), [1.5, 1.0])
        assert gc.ef_accumulate(None, v) is v  # None residual: passthrough
        e = gc.ef_residual(v, jnp.asarray([1.5, 0.0]))
        np.testing.assert_array_equal(np.asarray(e), [0.0, 1.0])

    def test_residual_equals_v_minus_sent_local(self):
        r = np.random.RandomState(0)
        tree = {"w": jnp.asarray(r.standard_normal((37, 13)).astype(np.float32))}
        p = gc.Int8EfPolicy()
        out, e = p.apply_local(tree, None)
        flat, meta = gc._flatten_tree(tree, p.block)
        q, s = gc.quantize_blocks(flat.reshape(1, -1), p.block)
        sent = gc.dequantize_blocks(q, s, p.block).reshape(-1)
        np.testing.assert_array_equal(np.asarray(e),
                                      np.asarray(flat - sent))

    def test_ef_prevents_bias_accumulation(self):
        """Exchanging the SAME gradient T times: the sum of applied means
        stays within one quantization step of T*g — with the residual
        zeroed each round instead, the bias would grow with T."""
        r = np.random.RandomState(1)
        g = {"w": jnp.asarray(r.standard_normal((40, 13)).astype(np.float32))}
        p = gc.Int8EfPolicy()
        T = 20
        e = None
        acc_ef = np.zeros((40, 13), np.float32)
        acc_no = np.zeros((40, 13), np.float32)
        for _ in range(T):
            out, e = p.apply_local(g, e)
            acc_ef += np.asarray(out["w"])
            out_no, _ = p.apply_local(g, None)
            acc_no += np.asarray(out_no["w"])
        target = T * np.asarray(g["w"])
        err_ef = np.abs(acc_ef - target).max()
        err_no = np.abs(acc_no - target).max()
        step = np.abs(np.asarray(g["w"])).max() / 127.0
        assert err_ef <= 2 * step, (err_ef, step)
        # without EF the per-step bias is multiplied by T wherever the
        # rounding is systematic; require EF to be strictly better
        assert err_ef < err_no, (err_ef, err_no)


# --------------------------------------------------------------------------
# wire-mode parity inside shard_map
# --------------------------------------------------------------------------

def _grad_pytree(R):
    """A REAL grad pytree per replica: grads of a small MLP loss on R
    different batch shards."""
    r = np.random.RandomState(0)
    params = {"w1": jnp.asarray(r.standard_normal((6, 8)).astype(np.float32)),
              "b1": jnp.zeros((8,), jnp.float32),
              "w2": jnp.asarray(r.standard_normal((8, 3)).astype(np.float32))}

    def loss_of(p, x, y):
        h = jnp.tanh(x @ p["w1"] + p["b1"])
        logits = h @ p["w2"]
        return -jnp.mean(jnp.take_along_axis(
            jax.nn.log_softmax(logits), y[:, None], 1))

    grads = []
    for i in range(R):
        x = jnp.asarray(r.standard_normal((8, 6)).astype(np.float32))
        y = jnp.asarray(r.randint(0, 3, 8))
        grads.append(jax.grad(loss_of)(params, x, y))
    return params, grads


@needs4
class TestWireParity:
    R = 4

    def _stacked(self, grads):
        return {k: jnp.stack([g[k] for g in grads]) for k in grads[0]}

    def test_int8_all_reduce_matches_fp32_mean(self):
        params, grads = _grad_pytree(self.R)
        mesh = Mesh(np.array(jax.devices()[:self.R]), ("data",))
        pol = gc.Int8EfPolicy()
        e0 = pol.residual_for(params, self.R)
        e0s = jnp.broadcast_to(e0[None], (self.R,) + e0.shape)
        specs = {k: P("data") for k in grads[0]}

        def body(t, e):
            t1 = {k: v[0] for k, v in t.items()}
            out, e2 = gc.compressed_all_reduce(t1, "data", pol, e[0])
            return {k: v[None] for k, v in out.items()}, e2[None]

        f = jax.jit(shard_map(body, mesh=mesh, in_specs=(specs, P("data")),
                              out_specs=(specs, P("data")), check_vma=False))
        out, e2 = f(self._stacked(grads), e0s)
        exact = {k: np.mean([np.asarray(g[k]) for g in grads], 0)
                 for k in grads[0]}
        stacked_abs = np.abs(np.concatenate(
            [np.stack([np.asarray(g[k]).ravel() for g in grads])
             for k in grads[0]], axis=1))
        bound = stacked_abs.max() / 127.0  # documented two-stage bound
        for k in exact:
            got = np.asarray(out[k][0])
            assert np.abs(got - exact[k]).max() <= bound, k
            for r in range(1, self.R):  # every replica sees the same mean
                np.testing.assert_array_equal(np.asarray(out[k][r]), got)
        # residual really carries this step's error
        assert np.abs(np.asarray(e2)).max() > 0

    @pytest.mark.parametrize("pol", ["fp32", "bf16", "int8_ef"])
    def test_reduce_scatter_gathers_to_all_reduce(self, pol):
        params, grads = _grad_pytree(self.R)
        mesh = Mesh(np.array(jax.devices()[:self.R]), ("data",))
        specs = {k: P("data") for k in grads[0]}
        policy = gc.resolve_policy(pol)

        def body(t):
            t1 = {k: v[0] for k, v in t.items()}
            shard, meta, _ = gc.compressed_reduce_scatter(t1, "data", policy)
            full = gc.tree_from_shards(shard, meta, "data")
            return {k: v[None] for k, v in full.items()}

        f = jax.jit(shard_map(body, mesh=mesh, in_specs=(specs,),
                              out_specs=specs, check_vma=False))
        out = f(self._stacked(grads))
        exact = {k: np.mean([np.asarray(g[k]) for g in grads], 0)
                 for k in grads[0]}
        tol = {"fp32": 1e-6, "bf16": 2e-2, "int8_ef": 5e-2}[pol]
        for k in exact:
            scale = max(np.abs(exact[k]).max(), 1e-3)
            assert np.abs(np.asarray(out[k][0]) - exact[k]).max() \
                <= tol * scale + tol * 0.1, k

    def test_int8_reduce_scatter_matches_all_reduce_shards(self):
        """The RS path is the AR path minus the gather: each replica's
        shard must equal its slice of the (pre-requantization) mean."""
        params, grads = _grad_pytree(self.R)
        mesh = Mesh(np.array(jax.devices()[:self.R]), ("data",))
        specs = {k: P("data") for k in grads[0]}
        pol = gc.Int8EfPolicy()

        def body(t):
            t1 = {k: v[0] for k, v in t.items()}
            shard, meta, _ = gc.compressed_reduce_scatter(t1, "data", pol)
            return shard[None]

        f = jax.jit(shard_map(body, mesh=mesh, in_specs=(specs,),
                              out_specs=P("data"), check_vma=False))
        shards = np.asarray(f(self._stacked(grads))).reshape(-1)
        exact = np.concatenate(
            [np.mean([np.asarray(g[k]) for g in grads], 0).ravel()
             for k in grads[0]])
        bound = max(np.abs(np.asarray(g[k])).max()
                    for g in grads for k in g) / 127.0
        assert np.abs(shards[:exact.size] - exact).max() <= bound


# --------------------------------------------------------------------------
# trainer threading
# --------------------------------------------------------------------------

@needs4
class TestTrainerThreading:
    def _loss_data(self):
        r = np.random.RandomState(3)
        params = {"w": jnp.asarray(r.standard_normal((6, 3)).astype(np.float32)
                                   * 0.3),
                  "b": jnp.zeros((3,), jnp.float32)}

        def loss_of(p, x, y):
            logits = x @ p["w"] + p["b"]
            return -jnp.mean(jnp.take_along_axis(
                jax.nn.log_softmax(logits), y[:, None], 1))

        x = jnp.asarray(r.standard_normal((16, 6)).astype(np.float32))
        y = jnp.asarray(r.randint(0, 3, 16))
        return params, loss_of, x, y

    def test_localsgd_policies_track_fp32(self):
        from paddle_tpu.distributed.localsgd import make_localsgd_train_step
        from paddle_tpu.optimizer import SGD
        mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
        params, loss_of, x, y = self._loss_data()
        curves = {}
        for pol in ("fp32", "bf16", "int8_ef"):
            step, state = make_localsgd_train_step(
                loss_of, params, SGD(0.1), mesh, k_steps=2, grad_comm=pol)
            assert ("comm_e" in state) == (pol == "int8_ef")
            losses = []
            for _ in range(8):
                state, loss = step(state, np.float32(0.1), x, y)
                losses.append(float(loss))
            curves[pol] = losses
            assert losses[-1] < losses[0]  # still optimizes
        np.testing.assert_allclose(curves["bf16"], curves["fp32"],
                                   rtol=0.02, atol=0.02)
        np.testing.assert_allclose(curves["int8_ef"], curves["fp32"],
                                   rtol=0.05, atol=0.05)

    def test_localsgd_int8_residual_is_per_replica(self):
        from paddle_tpu.distributed.localsgd import make_localsgd_train_step
        from paddle_tpu.optimizer import SGD
        mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
        params, loss_of, x, y = self._loss_data()
        step, state = make_localsgd_train_step(
            loss_of, params, SGD(0.1), mesh, k_steps=2, grad_comm="int8_ef")
        assert state["comm_e"].shape[0] == 4
        for i in range(2):  # second step is a sync step (k=2)
            state, _ = step(state, np.float32(0.1), x, y)
        e = np.asarray(state["comm_e"])
        assert np.abs(e).max() > 0  # residual populated after the sync
        # replicas saw different batch shards -> different residuals
        assert not np.allclose(e[0], e[1])

    def test_zero_stage2_policies(self):
        from paddle_tpu.distributed.zero import make_zero_train_step
        from paddle_tpu.optimizer import SGD
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("sharding",))

        def loss2(p, x):
            return jnp.mean((x @ p["w"]) ** 2)

        xz = jnp.asarray(np.random.RandomState(6)
                         .standard_normal((16, 8)).astype(np.float32))
        curves = {}
        for pol in ("fp32", "int8_ef"):
            p2 = {"w": jnp.asarray(np.random.RandomState(5)
                                   .standard_normal((8, 4)).astype(np.float32))}
            step, state = make_zero_train_step(loss2, p2, SGD(0.05), mesh,
                                               zero_stage=2, grad_comm=pol)
            assert ("comm_e" in state) == (pol == "int8_ef")
            losses = []
            for _ in range(6):
                state, loss = step(state, np.float32(0.05), xz)
                losses.append(float(loss))
            curves[pol] = losses
        np.testing.assert_allclose(curves["int8_ef"], curves["fp32"],
                                   rtol=0.05)

    def test_zero_offload_rejects_grad_comm(self):
        from paddle_tpu.distributed.zero import make_zero_train_step
        from paddle_tpu.optimizer import SGD
        mesh = Mesh(np.array(jax.devices()[:1]), ("sharding",))
        with pytest.raises(NotImplementedError, match="offload"):
            make_zero_train_step(lambda p, x: jnp.sum(p["w"] * x),
                                 {"w": jnp.ones((4,))}, SGD(0.1), mesh,
                                 offload=True, grad_comm="bf16")

    def test_functional_step_with_comm_monitor(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.jit.functional import make_train_step
        from paddle_tpu.optimizer import SGD
        from paddle_tpu.telemetry import TrainMonitor
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(10, 16), nn.Tanh(), nn.Linear(16, 4))
        mon = TrainMonitor()
        step, state = make_train_step(net, nn.CrossEntropyLoss(), SGD(0.1),
                                      grad_comm="int8_ef", monitor=mon)
        assert "comm_e" in state
        x = jnp.asarray(np.random.RandomState(0)
                        .standard_normal((8, 10)).astype(np.float32))
        y = jnp.asarray(np.random.RandomState(1).randint(0, 4, 8))
        for i in range(4):
            state, (loss, _) = step(state, jax.random.key(i),
                                    np.float32(0.1), [x], [y])
        assert np.isfinite(float(loss))
        comm = mon.summary()["comm"]
        assert comm["policy"] == "int8_ef"
        assert comm["savings"] >= 3.5, comm  # the acceptance contract
        evs = mon.events("comm")
        assert evs and evs[-1]["pre_bytes"] > evs[-1]["post_bytes"]

    def test_functional_fp32_state_and_events_unchanged(self):
        """Default grad_comm adds NO state leaf and NO comm events — the
        zero-diff contract for existing runs."""
        import paddle_tpu.nn as nn
        from paddle_tpu.jit.functional import make_train_step
        from paddle_tpu.optimizer import SGD
        from paddle_tpu.telemetry import TrainMonitor
        paddle.seed(0)
        net = nn.Linear(4, 2)
        mon = TrainMonitor()
        step, state = make_train_step(net, nn.CrossEntropyLoss(), SGD(0.1),
                                      monitor=mon)
        assert "comm_e" not in state
        x = jnp.ones((2, 4)); y = jnp.zeros((2,), jnp.int32)
        for i in range(2):
            state, _ = step(state, jax.random.key(i), np.float32(0.1),
                            [x], [y])
        assert mon.events("comm") == []
        assert mon.summary()["comm"] is None

    def test_accum_step_applies_at_boundary(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.jit.functional import make_accum_train_step
        from paddle_tpu.optimizer import SGD
        from paddle_tpu.telemetry import TrainMonitor
        paddle.seed(0)
        net = nn.Linear(6, 3)
        mon = TrainMonitor()
        step, state = make_accum_train_step(net, nn.CrossEntropyLoss(),
                                            SGD(0.1), 2, grad_comm="int8_ef",
                                            monitor=mon)
        assert "comm_e" in state
        x = jnp.asarray(np.random.RandomState(0)
                        .standard_normal((4, 6)).astype(np.float32))
        y = jnp.asarray(np.random.RandomState(1).randint(0, 3, 4))
        state, _ = step(state, jax.random.key(0), np.float32(0.1), [x], [y])
        # non-boundary step: residual untouched (no exchange happened)
        np.testing.assert_array_equal(np.asarray(state["comm_e"]), 0.0)
        state, _ = step(state, jax.random.key(1), np.float32(0.1), [x], [y])
        assert np.abs(np.asarray(state["comm_e"])).max() > 0
        # comm accounting is amortized by accum_steps: only every 2nd call
        # exchanges, so per-step bytes are half a full reduction's
        from paddle_tpu.distributed.grad_comm import wire_bytes
        params = {n: p._data for n, p in net.named_parameters()}
        full = wire_bytes(params, "int8_ef")
        evs = mon.events("comm")
        assert evs and evs[-1]["pre_bytes"] == full["pre_bytes"] // 2

    def test_sharded_gpt_int8_matches_fp32_step(self):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.models.gpt import (GPTConfig,
                                           make_sharded_gpt_train_step)
        from paddle_tpu.optimizer import SGD
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 1, "sharding_degree": 1}
        fleet.fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.fleet.get_hybrid_communicate_group()
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        num_attention_heads=2, max_position_embeddings=32,
                        compute_dtype="float32")
        x = jnp.asarray(np.random.RandomState(7).randint(0, 128, (2, 16)))
        curves = {}
        for pol in ("fp32", "int8_ef"):
            step, state = make_sharded_gpt_train_step(cfg, SGD(0.1), hcg,
                                                      grad_comm=pol)
            assert ("comm_e" in state) == (pol == "int8_ef")
            losses = []
            for i in range(4):
                state, loss = step(state, np.float32(0.1), jax.random.key(0),
                                   x, x)
                losses.append(float(loss))
            curves[pol] = losses
        np.testing.assert_allclose(curves["int8_ef"], curves["fp32"],
                                   rtol=0.02)

    def test_gpt_pipeline_rejects_grad_comm(self):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.models.gpt import (GPTConfig, GPTModel,
                                           make_gpt_train_step)
        from paddle_tpu.optimizer import SGD
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 2, "sharding_degree": 1}
        fleet.fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.fleet.get_hybrid_communicate_group()
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_attention_heads=2, max_position_embeddings=32,
                        compute_dtype="float32")
        with pytest.raises(NotImplementedError, match="grad_comm"):
            make_gpt_train_step(GPTModel(cfg), SGD(0.1), hcg,
                                grad_comm="int8_ef")


# --------------------------------------------------------------------------
# tiny-GPT convergence smoke
# --------------------------------------------------------------------------

@needs4
@pytest.mark.slow
def test_tiny_gpt_convergence_int8_vs_fp32():
    """~30 training steps on a tiny GPT: the int8_ef loss curve must track
    fp32 within tolerance — the EQuARX near-lossless claim at toy scale."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models.gpt import GPTConfig, make_sharded_gpt_train_step
    from paddle_tpu.optimizer import AdamW
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1}
    fleet.fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.fleet.get_hybrid_communicate_group()
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_attention_heads=2, max_position_embeddings=32,
                    compute_dtype="float32")
    r = np.random.RandomState(0)
    x = jnp.asarray(r.randint(0, 128, (4, 24)))
    y = jnp.asarray(r.randint(0, 128, (4, 24)))
    curves = {}
    for pol in ("fp32", "int8_ef"):
        step, state = make_sharded_gpt_train_step(
            cfg, AdamW(3e-3), hcg, grad_comm=pol)
        losses = []
        for i in range(30):
            state, loss = step(state, np.float32(3e-3), jax.random.key(i),
                               x, y)
            losses.append(float(loss))
        curves[pol] = losses
    fp, q = np.asarray(curves["fp32"]), np.asarray(curves["int8_ef"])
    assert q[-1] < q[0] * 0.8          # it converges
    # curve tracks fp32: mean relative gap within 5%, final within 10%
    assert np.mean(np.abs(q - fp) / np.abs(fp)) < 0.05, (fp[-5:], q[-5:])
    assert abs(q[-1] - fp[-1]) / abs(fp[-1]) < 0.10
