"""Multiprocess DataLoader + native shm ring tests.

Reference parity: fluid/dataloader/dataloader_iter.py:336 (worker processes,
shared-memory transport, order preservation) + pybind/reader_py.cc
(BlockingQueue).  The determinism contract: output order equals sampler
order regardless of worker count or timing.
"""

import os
import time

import numpy as np
import pytest

from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.io.shm_queue import ShmQueue, decode_batch, encode_batch


class ArrayDataset(Dataset):
    def __init__(self, n=64, decode_ms=0.0):
        self.n = n
        self.decode_ms = decode_ms

    def __getitem__(self, i):
        if self.decode_ms:
            time.sleep(self.decode_ms / 1000.0)
        return (np.full((4, 4), i, np.float32), np.int64(i))

    def __len__(self):
        return self.n


class FailingDataset(ArrayDataset):
    def __getitem__(self, i):
        if i == 13:
            raise ValueError("boom at 13")
        return super().__getitem__(i)


class TestShmQueue:
    def test_roundtrip_and_wrap(self):
        q = ShmQueue(f"/pt_ut_{os.getpid()}", capacity=1 << 14)
        payloads = [bytes([i % 251]) * (i * 37 % 3000 + 1) for i in range(100)]
        # interleave so the ring wraps many times but never overfills
        pending = []
        for p in payloads:
            q.put(p, timeout=5)
            pending.append(p)
            if len(pending) >= 3:
                assert q.get(timeout=5) == pending.pop(0)
        while pending:
            assert q.get(timeout=5) == pending.pop(0)

    def test_batch_codec(self):
        batch = {"x": np.arange(6, dtype=np.float32).reshape(2, 3),
                 "meta": ["a", ("b", np.ones(2, np.int64))]}
        tag, out = decode_batch(encode_batch(42, batch))
        assert tag == 42
        np.testing.assert_array_equal(out["x"], batch["x"])
        assert out["meta"][0] == "a"
        np.testing.assert_array_equal(out["meta"][1][1], batch["meta"][1][1])

    def test_timeout(self):
        q = ShmQueue(f"/pt_ut_to_{os.getpid()}", capacity=1 << 12)
        with pytest.raises(TimeoutError):
            q.get(timeout=0.2)


class TestMultiprocessLoader:
    @pytest.mark.parametrize("num_workers", [1, 3])
    def test_order_matches_serial(self, num_workers):
        ds = ArrayDataset(64)
        serial = [(np.asarray(x._data), np.asarray(y._data))
                  for x, y in DataLoader(ds, batch_size=8, num_workers=0)]
        par = [(np.asarray(x._data), np.asarray(y._data))
               for x, y in DataLoader(ds, batch_size=8,
                                      num_workers=num_workers)]
        assert len(serial) == len(par) == 8
        for (sx, sy), (px, py) in zip(serial, par):
            np.testing.assert_array_equal(sx, px)
            np.testing.assert_array_equal(sy, py)

    def test_mp_queue_fallback_order(self):
        ds = ArrayDataset(32)
        par = [np.asarray(y._data)
               for _, y in DataLoader(ds, batch_size=8, num_workers=2,
                                      use_shared_memory=False)]
        np.testing.assert_array_equal(np.concatenate(par), np.arange(32))

    def test_forkserver_start_method(self, monkeypatch):
        """PADDLE_TPU_WORKER_START=forkserver: the fork-immune path (for
        picklable datasets) produces the same ordered stream — keeps the
        documented escape hatch from the fork-by-default tradeoff working."""
        monkeypatch.setenv("PADDLE_TPU_WORKER_START", "forkserver")
        ds = ArrayDataset(32)
        par = [np.asarray(y._data)
               for _, y in DataLoader(ds, batch_size=8, num_workers=2)]
        np.testing.assert_array_equal(np.concatenate(par), np.arange(32))

    def test_worker_error_propagates(self):
        loader = DataLoader(FailingDataset(32), batch_size=8, num_workers=2)
        with pytest.raises(RuntimeError, match="boom at 13"):
            list(loader)

    def test_worker_init_fn_runs(self):
        calls = []

        # worker_init_fn runs in the child; prove it via a side effect the
        # child can ship back — mutate the dataset copy so sample 0 changes
        class InitDataset(ArrayDataset):
            offset = 0

            def __getitem__(self, i):
                return (np.full((2,), i + self.offset, np.float32),)

        def init_fn(worker_id):
            InitDataset.offset = 100

        out = [np.asarray(x._data)
               for (x,) in DataLoader(InitDataset(8), batch_size=4,
                                      num_workers=1, worker_init_fn=init_fn)]
        assert out[0][0, 0] == 100.0

    def test_workers_scale_on_decode_heavy_dataset(self):
        """The round-1 loader ignored num_workers: one GIL thread.  With
        process workers a sleep-decode dataset must scale.  The decode work
        (64 x 20ms = 1.28s serial) dominates fork/attach overhead, and the
        bound is deliberately loose to stay robust on loaded CI hosts."""
        ds = ArrayDataset(64, decode_ms=20.0)

        def run(workers):
            t0 = time.perf_counter()
            n = sum(1 for _ in DataLoader(ds, batch_size=4,
                                          num_workers=workers))
            assert n == 16
            return time.perf_counter() - t0

        # Timing-based: retry a couple of times so a loaded CI host (e.g.
        # another pytest worker stealing cores) doesn't flake the suite.
        for attempt in range(3):
            t1 = run(1)
            t4 = run(4)
            if t4 < t1 / 1.5:
                return
        assert t4 < t1 / 1.5, (t1, t4)
