"""Sharded checkpoint + elastic resume tests (VERDICT round-1 #8).

The load-bearing scenario is the elastic rescale story promised by
fleet/elastic.py: train on an 8-way mesh, checkpoint, resume on a 4-way
mesh, and the loss trajectory must continue exactly as if the run had never
stopped (reference counterpart: sharding_optimizer state save/load +
auto_checkpoint.py:71 resume).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.zero import make_zero_train_step
from paddle_tpu.optimizer import Adam

needs8 = pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 devices")


def _mlp_params(seed=0):
    r = np.random.RandomState(seed)
    mk = lambda *s: jnp.asarray(r.standard_normal(s).astype(np.float32) * 0.1)
    return {"w1": mk(16, 32), "b1": mk(32), "w2": mk(32, 8), "b2": mk(8)}


def _loss_of(params, x, y):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))


def _batch(seed=1):
    r = np.random.RandomState(seed)
    return (jnp.asarray(r.standard_normal((16, 16)).astype(np.float32)),
            jnp.asarray(r.randint(0, 8, 16)))


def _sharding_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("sharding",))


def _shardings_of(state):
    return jax.tree_util.tree_map(
        lambda a: a.sharding if isinstance(a, jax.Array) else None, state)


@needs8
class TestShardedCheckpoint:
    def test_roundtrip_same_mesh(self, tmp_path):
        mesh = _sharding_mesh(8)
        step, state = make_zero_train_step(_loss_of, _mlp_params(), Adam(1e-2),
                                           mesh, zero_stage=2)
        x, y = _batch()
        state, _ = step(state, np.float32(1e-2), x, y)
        ckpt.save(state, str(tmp_path / "c1"))
        loaded = ckpt.load(str(tmp_path / "c1"), target=state,
                           shardings=_shardings_of(state))
        for (ka, a), (kb, b) in zip(
                sorted(ckpt._flatten(state).items()),
                sorted(ckpt._flatten(loaded).items())):
            assert ka == kb
            if isinstance(a, jax.Array):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                              err_msg=ka)

    @pytest.mark.parametrize("stage", [2, 3])
    def test_elastic_rescale_8_to_4(self, tmp_path, stage):
        """save on sharding=8, resume on sharding=4: loss continuity."""
        x, y = _batch()
        lr = np.float32(1e-2)

        # uninterrupted reference run on 8 devices
        mesh8 = _sharding_mesh(8)
        step8, state8 = make_zero_train_step(_loss_of, _mlp_params(),
                                             Adam(1e-2), mesh8,
                                             zero_stage=stage)
        ref_losses = []
        for _ in range(6):
            state8, loss = step8(state8, lr, x, y)
            ref_losses.append(float(loss))

        # interrupted run: 3 steps on 8, checkpoint, resume 3 on 4
        mesh8b = _sharding_mesh(8)
        stepA, stateA = make_zero_train_step(_loss_of, _mlp_params(),
                                             Adam(1e-2), mesh8b,
                                             zero_stage=stage)
        for _ in range(3):
            stateA, _ = stepA(stateA, lr, x, y)
        ckpt.save(stateA, str(tmp_path / "resc"))

        mesh4 = _sharding_mesh(4)
        stepB, stateB0 = make_zero_train_step(_loss_of, _mlp_params(),
                                              Adam(1e-2), mesh4,
                                              zero_stage=stage)
        stateB = ckpt.load(str(tmp_path / "resc"), target=stateB0,
                           shardings=_shardings_of(stateB0))
        resumed = []
        for _ in range(3):
            stateB, loss = stepB(stateB, lr, x, y)
            resumed.append(float(loss))
        np.testing.assert_allclose(resumed, ref_losses[3:], rtol=2e-5,
                                   atol=2e-6)

    def test_chunked_large_leaf(self, tmp_path, monkeypatch):
        monkeypatch.setattr(ckpt, "_MAX_CHUNK_BYTES", 256)
        arr = np.arange(1024, dtype=np.float32).reshape(32, 32)
        state = {"big": jnp.asarray(arr), "s": jnp.asarray(3.0)}
        ckpt.save(state, str(tmp_path / "chunked"))
        files = [f for f in os.listdir(tmp_path / "chunked")
                 if f.startswith("big") and f.endswith(".npy")]
        assert len(files) > 1, "large leaf was not split into chunks"
        loaded = ckpt.load(str(tmp_path / "chunked"), target=state)
        np.testing.assert_array_equal(np.asarray(loaded["big"]), arr)
        np.testing.assert_allclose(float(np.asarray(loaded["s"])), 3.0)

    def test_async_save(self, tmp_path):
        state = {"a": jnp.arange(16.0), "b": {"c": jnp.ones((4, 4))}}
        h = ckpt.save(state, str(tmp_path / "async"), async_save=True)
        h.wait()
        assert h.done()
        loaded = ckpt.load(str(tmp_path / "async"), target=state)
        np.testing.assert_array_equal(np.asarray(loaded["b"]["c"]),
                                      np.ones((4, 4)))

    @needs8
    def test_replicated_leaf_saved_once(self, tmp_path):
        mesh = _sharding_mesh(8)
        rep = jax.device_put(jnp.ones((8, 8)), NamedSharding(mesh, P()))
        sharded = jax.device_put(jnp.arange(64.0).reshape(8, 8),
                                 NamedSharding(mesh, P("sharding")))
        ckpt.save({"rep": rep, "sh": sharded}, str(tmp_path / "dedup"))
        files = os.listdir(tmp_path / "dedup")
        rep_files = [f for f in files if f.startswith("rep")]
        sh_files = [f for f in files if f.startswith("sh")]
        assert len(rep_files) == 1, f"replicated leaf duplicated: {rep_files}"
        assert len(sh_files) == 8, f"expected 8 shard files: {sh_files}"

    def test_missing_leaf_raises(self, tmp_path):
        ckpt.save({"a": jnp.ones(3)}, str(tmp_path / "m"))
        with pytest.raises(KeyError):
            ckpt.load(str(tmp_path / "m"), target={"a": jnp.ones(3),
                                                   "b": jnp.ones(3)})


@needs8
def test_resave_smaller_world_ignores_stale_partials(tmp_path, monkeypatch):
    """Re-saving to the same dir after a rescale must not resurrect stale
    per-process manifests (round-2 review finding)."""
    d = str(tmp_path / "resave")
    state_old = {"w": jnp.zeros((8,))}
    # simulate an old 8-process save: write a stale partial manifest claiming
    # a chunk with old data
    ckpt.save(state_old, d)
    import json
    old_chunk = "w.stale.p1.npy"
    np.save(os.path.join(d, old_chunk[:-4] + ".npy"),
            np.full((8,), 99.0, np.float32))
    with open(os.path.join(d, "manifest.p1.json"), "w") as f:
        json.dump({"leaves": {"w": {"kind": "array", "shape": [8],
                                    "dtype": "float32",
                                    "chunks": [{"file": "w.stale.p1.npy",
                                                "box": [[0, 8]]}]}},
                   "format": 1, "process_count": 8}, f)
    # fresh single-process save of NEW data to the same directory
    state_new = {"w": jnp.arange(8.0)}
    ckpt.save(state_new, d)
    loaded = ckpt.load(d, target=state_new)
    np.testing.assert_array_equal(np.asarray(loaded["w"]), np.arange(8.0))


class TestCorruptDetection:
    """ISSUE 20 satellite: ``load()`` on a torn directory must raise a
    structured ``CorruptCheckpoint`` naming the damage — never return
    silently wrong tensors, never crash with a raw numpy error."""

    def test_truncated_npy_raises_corrupt(self, tmp_path):
        d = str(tmp_path / "torn")
        state = {"w": jnp.arange(4096.0), "b": jnp.ones((8,))}
        ckpt.save(state, d)
        victim = sorted(f for f in os.listdir(d)
                        if f.startswith("w") and f.endswith(".npy"))[0]
        p = os.path.join(d, victim)
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) // 2)
        with pytest.raises(ckpt.CorruptCheckpoint,
                           match="torn|unreadable"):
            ckpt.load(d, target=state)

    def test_missing_chunk_raises_corrupt(self, tmp_path):
        d = str(tmp_path / "gone")
        state = {"w": jnp.arange(16.0)}
        ckpt.save(state, d)
        for f in os.listdir(d):
            if f.startswith("w") and f.endswith(".npy"):
                os.remove(os.path.join(d, f))
        with pytest.raises(ckpt.CorruptCheckpoint, match="missing"):
            ckpt.load(d, target=state)

    def test_missing_manifest_raises_corrupt(self, tmp_path):
        d = str(tmp_path / "nomanifest")
        state = {"w": jnp.arange(16.0)}
        ckpt.save(state, d)
        os.remove(os.path.join(d, "manifest.json"))
        with pytest.raises(ckpt.CorruptCheckpoint, match="never committed"):
            ckpt.load(d, target=state)

    def test_wrong_shape_chunk_raises_corrupt(self, tmp_path):
        d = str(tmp_path / "mixed")
        state = {"w": jnp.arange(16.0)}
        ckpt.save(state, d)
        victim = [f for f in os.listdir(d)
                  if f.startswith("w") and f.endswith(".npy")][0]
        np.save(os.path.join(d, victim), np.zeros((3,), np.float32))
        with pytest.raises(ckpt.CorruptCheckpoint, match="shape"):
            ckpt.load(d, target=state)

    def test_bf16_roundtrip_bit_exact(self, tmp_path):
        """Extension dtypes store as same-width uint views; the logical
        dtype must come back bit-exact (np.save of raw ml_dtypes bf16
        reloads as void — the regression this pins)."""
        d = str(tmp_path / "bf16")
        w = jnp.arange(64.0, dtype=jnp.bfloat16) * jnp.bfloat16(0.1)
        ckpt.save({"w": w}, d)
        loaded = ckpt.load(d, target={"w": w})
        got = np.asarray(loaded["w"])
        assert got.dtype == np.asarray(w).dtype
        np.testing.assert_array_equal(got.view(np.uint16),
                                      np.asarray(w).view(np.uint16))
