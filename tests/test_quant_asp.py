"""Quantization (QAT/PTQ) + ASP 2:4 sparsity tests (SURVEY rows 33-34)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


class TestFakeQuant:
    def test_quant_dequant_grid(self):
        from paddle_tpu.quantization import fake_quant_dequant
        x = jnp.asarray(np.linspace(-1, 1, 11).astype(np.float32))
        out = np.asarray(fake_quant_dequant(x, 1.0, bits=8))
        # values land on the 127-step grid
        np.testing.assert_allclose(out * 127.0, np.round(out * 127.0),
                                   atol=1e-4)
        np.testing.assert_allclose(out, np.asarray(x), atol=1.0 / 127.0)

    def test_ste_gradient(self):
        from paddle_tpu.quantization import fake_quant_dequant
        g = jax.grad(lambda x: jnp.sum(fake_quant_dequant(x, 2.0)))(
            jnp.asarray([0.3, -0.7]))
        np.testing.assert_allclose(np.asarray(g), [1.0, 1.0])  # pass-through


class TestQAT:
    def test_qat_trains_and_quantizes(self):
        from paddle_tpu.quantization import ImperativeQuantAware, QuantedLinear
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
        ImperativeQuantAware().quantize(model)
        quanted = [s for s in model.sublayers() if isinstance(s, QuantedLinear)]
        assert len(quanted) == 2
        opt = paddle.optimizer.SGD(0.5, parameters=model.parameters())
        r = np.random.RandomState(0)
        X = r.standard_normal((64, 8)).astype(np.float32)
        yv = (X[:, 0] > 0).astype(np.int64)
        xt, yt = paddle.to_tensor(X), paddle.to_tensor(yv)
        first = None
        for i in range(40):
            loss = nn.functional.cross_entropy(model(xt), yt)
            loss.backward()
            opt.step()
            opt.clear_grad()
            if first is None:
                first = float(loss)
        assert float(loss) < first / 2, (first, float(loss))


class TestPTQ:
    def test_int8_conversion_accuracy(self):
        from paddle_tpu.quantization import PostTrainingQuantization
        paddle.seed(1)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        r = np.random.RandomState(1)
        X = r.standard_normal((16, 8)).astype(np.float32)
        ref = np.asarray(model(paddle.to_tensor(X))._data)

        ptq = PostTrainingQuantization(model)
        ptq.calibrate([paddle.to_tensor(X)])
        ptq.convert()
        out = np.asarray(model(paddle.to_tensor(X))._data)
        # int8 weight quantization error is bounded and small relative to
        # activations of order ~1
        assert np.abs(out - ref).max() < 0.1, np.abs(out - ref).max()
        # weights are genuinely int8 now
        from paddle_tpu.quantization import _Int8Linear
        int8_layers = [s for s in model.sublayers()
                       if isinstance(s, _Int8Linear)]
        assert len(int8_layers) == 2
        assert int8_layers[0].w_int8._data.dtype == jnp.int8


class TestASP:
    def test_create_mask_2_4(self):
        from paddle_tpu.incubate.asp import check_sparsity, create_mask
        r = np.random.RandomState(0)
        w = jnp.asarray(r.standard_normal((8, 16)).astype(np.float32))
        mask = create_mask(w, 2, 4)
        assert np.asarray(mask).reshape(-1, 4).sum(axis=1).max() == 2
        pruned = jnp.where(mask, w, 0)
        assert check_sparsity(pruned, 2, 4)
        # kept entries are the two largest |values| of each block
        blocks = np.abs(np.asarray(w)).reshape(-1, 4)
        kept = np.asarray(mask).reshape(-1, 4)
        for b, k in zip(blocks, kept):
            assert set(np.where(k)[0]) == set(np.argsort(-b, kind="stable")[:2])

    def test_prune_model_and_decorated_optimizer_remask(self):
        from paddle_tpu.incubate import asp
        paddle.seed(2)
        model = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 4))
        asp.prune_model(model, 2, 4)
        for lin in (model[0], model[2]):
            assert asp.check_sparsity(lin.weight._data, 2, 4)
        opt = asp.decorate(paddle.optimizer.SGD(
            0.1, parameters=model.parameters()))
        X = paddle.to_tensor(np.random.RandomState(3)
                             .standard_normal((16, 8)).astype(np.float32))
        y = paddle.to_tensor(np.random.RandomState(4).randint(0, 4, 16))
        for _ in range(3):
            loss = nn.functional.cross_entropy(model(X), y)
            loss.backward()
            opt.step()   # dense grads revive zeros; decorate must re-mask
            opt.clear_grad()
        for lin in (model[0], model[2]):
            assert asp.check_sparsity(lin.weight._data, 2, 4), \
                "optimizer step broke the 2:4 pattern"

    def test_excluded_layers(self):
        from paddle_tpu.incubate import asp
        paddle.seed(3)
        model = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 8))
        asp.set_excluded_layers(param_names=["0.weight"])
        try:
            asp.prune_model(model, 2, 4)
            assert not asp.check_sparsity(model[0].weight._data, 2, 4)
            assert asp.check_sparsity(model[1].weight._data, 2, 4)
        finally:
            asp.reset_excluded_layers()


class TestQATUnderJit:
    def test_act_scale_calibrates_through_jitted_steps(self):
        """The activation-scale buffer must keep updating when the QAT model
        trains through a jitted functional step (round-2 review: a Python
        observer bakes its initial scale as a compile-time constant)."""
        from paddle_tpu.jit.functional import make_train_step
        from paddle_tpu.quantization import ImperativeQuantAware, QuantedLinear
        import paddle_tpu.nn.functional as F
        paddle.seed(5)
        model = nn.Sequential(nn.Linear(4, 4))
        ImperativeQuantAware().quantize(model)
        ql = model[0]
        assert isinstance(ql, QuantedLinear)
        opt = paddle.optimizer.SGD(0.01, parameters=model.parameters())
        step, state = make_train_step(model, lambda o, y: F.cross_entropy(o, y), opt)
        r = np.random.RandomState(5)
        x = jnp.asarray(r.standard_normal((8, 4)).astype(np.float32) * 7.0)
        y = jnp.asarray(r.randint(0, 4, 8))
        state, _ = step(state, jax.random.key(0), np.float32(0.01), (x,), (y,))
        # scale buffer lives in the jitted state's buffers; it must reflect
        # the big activations (≈7σ inputs → scale far above the zero init)
        scales = [float(np.asarray(v))
                  for k, v in state["buffers"].items() if "act_scale" in k]
        assert scales and max(scales) > 1.0, (scales, list(state["buffers"]))


class TestConvQuant:
    """Conv + per-channel depth (VERDICT r2 missing #8; ≙ reference slim
    conv/channel-wise passes, fluid/contrib/slim/quantization)."""

    def _conv_model(self, seed=3):
        paddle.seed(seed)
        return nn.Sequential(
            nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
            nn.Conv2D(8, 8, 3, padding=1, groups=2), nn.ReLU(),
            nn.AdaptiveAvgPool2D(1), nn.Flatten(), nn.Linear(8, 4))

    def test_per_channel_weight_scale_shapes(self):
        from paddle_tpu.quantization import _weight_scale
        w = jnp.asarray(np.random.RandomState(0).randn(8, 3, 3, 3),
                        jnp.float32)
        s = _weight_scale(w, "channel_wise_abs_max", channel_axis=0)
        assert s.shape == (8, 1, 1, 1)
        np.testing.assert_allclose(
            np.asarray(s).ravel(),
            np.abs(np.asarray(w)).max(axis=(1, 2, 3)), rtol=1e-6)
        st = _weight_scale(w, "abs_max")
        assert st.shape == ()

    def test_qat_wraps_conv_and_trains(self):
        from paddle_tpu.quantization import (ImperativeQuantAware,
                                             QuantedConv2D, QuantedLinear)
        model = self._conv_model()
        ImperativeQuantAware(
            weight_quantize_type="channel_wise_abs_max").quantize(model)
        kinds = [type(l).__name__ for l in model._sub_layers.values()]
        assert kinds.count("QuantedConv2D") == 2
        assert kinds.count("QuantedLinear") == 1

        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=model.parameters())
        x = paddle.to_tensor(np.random.RandomState(0).randn(
            4, 3, 8, 8).astype("float32"))
        y = paddle.to_tensor(np.array([0, 1, 2, 3], "int64"))
        losses = []
        for _ in range(6):
            out = model(x)
            loss = nn.functional.cross_entropy(out, y)
            loss.backward()
            opt.step(); opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0], losses
        # the EMA activation observer calibrated
        assert float(model._sub_layers["0"].act_scale.numpy()) > 0

    def test_ptq_conv_int8_agrees_with_float(self):
        """PTQ over a conv model end-to-end: quantized eval predictions
        agree with the float model (the quantized-ResNet-accuracy check,
        scaled to CI: same block structure, synthetic data)."""
        from paddle_tpu.quantization import PostTrainingQuantization
        model = self._conv_model()
        model.eval()
        r = np.random.RandomState(1)
        xs = [paddle.to_tensor(r.randn(8, 3, 8, 8).astype("float32"))
              for _ in range(3)]
        float_preds = [np.asarray(model(x)._data).argmax(-1) for x in xs]

        ptq = PostTrainingQuantization(model)
        ptq.calibrate(xs)
        qmodel = ptq.convert()
        kinds = [type(l).__name__ for l in qmodel._sub_layers.values()]
        assert kinds.count("_Int8Conv2D") == 2
        assert kinds.count("_Int8Linear") == 1

        agree = total = 0
        for x, fp in zip(xs, float_preds):
            qp = np.asarray(qmodel(x)._data).argmax(-1)
            agree += int((qp == fp).sum()); total += len(fp)
        assert agree / total >= 0.85, f"int8 agreement {agree}/{total}"

    def test_ptq_resnet_basicblock_eval(self):
        """Quantized-ResNet eval check on the real resnet18 architecture
        (cut to CIFAR-size inputs): int8 model top-1 agrees with float."""
        from paddle_tpu.quantization import PostTrainingQuantization
        from paddle_tpu.vision.models import resnet18
        paddle.seed(7)
        model = resnet18(num_classes=10)
        model.eval()
        r = np.random.RandomState(2)
        xs = [paddle.to_tensor(r.randn(2, 3, 32, 32).astype("float32"))
              for _ in range(2)]
        float_preds = [np.asarray(model(x)._data).argmax(-1) for x in xs]
        qmodel = PostTrainingQuantization(model).calibrate(xs).convert()
        agree = total = 0
        for x, fp in zip(xs, float_preds):
            qp = np.asarray(qmodel(x)._data).argmax(-1)
            agree += int((qp == fp).sum()); total += len(fp)
        assert agree / total >= 0.75, f"int8 resnet agreement {agree}/{total}"
