"""Numpy/naive-oracle tests for the fused softmax CE (ops/loss.py)."""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.ops.loss import (softmax_cross_entropy_mean,
                                 softmax_cross_entropy_weighted_mean)


def _naive(lg, lb):
    lp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)
    return -jnp.take_along_axis(lp, lb[..., None], -1)[..., 0]


class TestFusedCE:
    def test_fwd_and_grad_parity(self):
        rng = np.random.RandomState(0)
        logits = jnp.asarray(rng.standard_normal((4, 16, 97)).astype("float32"))
        labels = jnp.asarray(rng.randint(0, 97, (4, 16)))
        l1 = softmax_cross_entropy_mean(logits, labels)
        l2 = _naive(logits, labels).mean()
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
        g1 = jax.grad(lambda x: softmax_cross_entropy_mean(x, labels))(logits)
        g2 = jax.grad(lambda x: _naive(x, labels).mean())(logits)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-7)

    def test_weighted_parity_with_ignore_mask(self):
        rng = np.random.RandomState(1)
        logits = jnp.asarray(rng.standard_normal((2, 8, 33)).astype("float32"))
        labels_raw = rng.randint(0, 33, (2, 8))
        labels_raw[0, :4] = -100  # ignore-index convention
        valid = jnp.asarray(labels_raw >= 0)
        safe = jnp.asarray(np.where(labels_raw >= 0, labels_raw, 0))

        def naive_masked(x):
            nll = _naive(x, safe)
            return jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1)

        l1 = softmax_cross_entropy_weighted_mean(logits, safe, valid)
        np.testing.assert_allclose(float(l1), float(naive_masked(logits)), rtol=1e-6)
        g1 = jax.grad(lambda x: softmax_cross_entropy_weighted_mean(x, safe, valid))(logits)
        g2 = jax.grad(naive_masked)(logits)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-7)
        # ignored rows contribute exactly zero gradient
        assert float(jnp.abs(g1[0, :4]).max()) == 0.0

    def test_bf16_logits_grad_dtype_and_accuracy(self):
        rng = np.random.RandomState(2)
        logits = jnp.asarray(rng.standard_normal((4, 64)).astype("float32"))
        labels = jnp.asarray(rng.randint(0, 64, (4,)))
        g32 = jax.grad(lambda x: softmax_cross_entropy_mean(x, labels))(logits)
        g16 = jax.grad(lambda x: softmax_cross_entropy_mean(x, labels))(
            logits.astype(jnp.bfloat16))
        assert g16.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(g16, dtype="float32"),
                                   np.asarray(g32), atol=5e-3)

    def test_all_masked_is_zero_not_nan(self):
        logits = jnp.zeros((2, 4, 8))
        labels = jnp.zeros((2, 4), jnp.int32)
        w = jnp.zeros((2, 4))
        loss = softmax_cross_entropy_weighted_mean(logits, labels, w)
        assert float(loss) == 0.0
        g = jax.grad(lambda x: softmax_cross_entropy_weighted_mean(x, labels, w))(logits)
        assert np.all(np.asarray(g) == 0.0)


def test_fused_ce_residuals_stay_compute_dtype():
    """The fused CE must never SAVE an fp32 (..., V) tensor between fwd and
    bwd (the whole point vs log_softmax: 1.6GB of HBM at bench shapes).
    eval_shape proves the residual pytree holds only the bf16 logits plus
    O(B*L) fp32 reductions."""
    from paddle_tpu.ops.loss import _ce_fwd, _cew_fwd

    B, L, V = 4, 128, 50304
    logits = jax.ShapeDtypeStruct((B, L, V), jnp.bfloat16)
    labels = jax.ShapeDtypeStruct((B, L), jnp.int32)
    weights = jax.ShapeDtypeStruct((B, L), jnp.float32)

    for fwd, args in ((_ce_fwd, (logits, labels)),
                      (_cew_fwd, (logits, labels, weights))):
        _, res = jax.eval_shape(fwd, *args)
        for leaf in jax.tree_util.tree_leaves(res):
            big = leaf.shape and leaf.shape[-1] >= V
            assert not (big and leaf.dtype == jnp.float32), (
                f"fp32 (...,V) residual {leaf.shape} saved by {fwd.__name__}")
