"""Paged speculative continuous batching (PagedSpeculativeBatchingEngine):
the two serving accelerations composed.  The draft pool shares the
target's block tables and allocator; the spec round runs the SAME
_spec_round_core with pools wrapped as PagedKV — so outputs must stay
bit-lossless vs plain greedy (and vs the contiguous speculative engine),
and the paged allocator's deferral/preemption must hold under tight
pools.  Beyond-reference (the snapshot has no serving scheduler)."""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTConfig, GPTModel
from paddle_tpu.serving import (PagedSpeculativeBatchingEngine,
                                SpeculativeBatchingEngine)


import functools


@functools.lru_cache(maxsize=None)
def _models(kv=None):
    """Memoized per kv flag: all tests share the same model OBJECTS, so
    compiled serving programs (cached on the model) are built once per
    signature for the whole file instead of once per test."""
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=96,
                    compute_dtype="float32", kv_cache_dtype=kv)
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    dcfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=1,
                     num_attention_heads=4, max_position_embeddings=96,
                     compute_dtype="float32", kv_cache_dtype=kv)
    draft = GPTModel(dcfg)
    dparams = {n: p._data for n, p in draft.named_parameters()}
    return model, params, draft, dparams


def _solo(model, params, p, n):
    out = model.generate(params, jnp.asarray([p], jnp.int32), n,
                         greedy=True)
    return [int(t) for t in np.asarray(out)[0]]


REQS = [([5, 17, 3], 10), ([40, 2], 6), ([61], 8), ([9, 9, 1], 7)]


class TestPagedSpeculative:
    @pytest.mark.parametrize("K", [1, 3])
    def test_lossless_vs_solo_and_contiguous(self, K):
        """Mixed budgets through 2 slots (retirement + reuse): outputs
        equal plain greedy solo AND the contiguous speculative engine,
        token for token, with the same round count."""
        model, params, draft, dparams = _models()
        paged = PagedSpeculativeBatchingEngine(
            model, params, draft, dparams, max_slots=2, max_len=48,
            draft_k=K, prompt_buckets=[8], block_size=4)
        rids = [paged.add_request(p, n) for p, n in REQS]
        got = paged.run_to_completion(max_ticks=300)
        cont = SpeculativeBatchingEngine(
            model, params, draft, dparams, max_slots=2, max_len=48,
            draft_k=K, prompt_buckets=[8])
        rids_c = [cont.add_request(p, n) for p, n in REQS]
        got_c = cont.run_to_completion(max_ticks=300)
        for rid, rc, (p, n) in zip(rids, rids_c, REQS):
            want = _solo(model, params, p, n)
            assert got[rid] == want, f"paged diverged (K={K})"
            assert got_c[rc] == want
        assert paged.rounds == cont.rounds      # same acceptance schedule
        assert paged.blocks_in_use == 0

    def test_perfect_draft_minimal_rounds(self):
        """draft == target: every proposal accepted — one request of N
        tokens finishes in exactly ceil((N-1)/(K+1)) rounds (the
        acceptance-degradation regression observable, now on the paged
        layout)."""
        model, params, draft, dparams = _models()
        K, N = 3, 13
        eng = PagedSpeculativeBatchingEngine(
            model, params, model, params, max_slots=1, max_len=48,
            draft_k=K, prompt_buckets=[8], block_size=4)
        rid = eng.add_request([5, 17, 3], N)
        got = eng.run_to_completion(max_ticks=100)
        assert got[rid] == _solo(model, params, [5, 17, 3], N)
        assert eng.rounds == -(-(N - 1) // (K + 1))

    def test_tight_pool_preempts_and_stays_exact(self):
        """Two long requests cannot both fit: the younger is preempted
        and rerun, outputs stay greedy-exact, high water respects the
        cap — the paged allocator composing with spec growth spans."""
        model, params, draft, dparams = _models()
        eng = PagedSpeculativeBatchingEngine(
            model, params, draft, dparams, max_slots=2, max_len=48,
            draft_k=2, prompt_buckets=[8], block_size=4, num_blocks=10)
        r0 = eng.add_request([5, 17, 3], 24)   # P+mnt+K-1 = 33 -> 9 blocks
        r1 = eng.add_request([40, 2], 24)
        got = eng.run_to_completion(max_ticks=500)
        assert eng.preemptions >= 1
        assert eng.blocks_high_water <= 10
        assert got[r0] == _solo(model, params, [5, 17, 3], 24)
        assert got[r1] == _solo(model, params, [40, 2], 24)

    def test_int8_pools(self):
        """int8 target AND draft pools through the shared tables."""
        model, params, draft, dparams = _models(kv="int8")
        eng = PagedSpeculativeBatchingEngine(
            model, params, draft, dparams, max_slots=2, max_len=48,
            draft_k=2, prompt_buckets=[8], block_size=8)
        rids = [eng.add_request(p, n) for p, n in REQS[:3]]
        got = eng.run_to_completion(max_ticks=300)
        for rid, (p, n) in zip(rids, REQS[:3]):
            assert got[rid] == _solo(model, params, p, n)

    def test_program_count_bounded(self):
        model, params, draft, dparams = _models()
        model.__dict__.pop("_serving_programs", None)

        def make():
            return PagedSpeculativeBatchingEngine(
                model, params, draft, dparams, max_slots=2, max_len=48,
                draft_k=2, prompt_buckets=[8], block_size=4)

        eng = make()
        for p, n in REQS[:3]:
            eng.add_request(p, n)
        eng.run_to_completion(max_ticks=300)
        n_progs = len(model._serving_programs)
        eng2 = make()
        eng2.add_request(REQS[3][0], REQS[3][1])
        eng2.run_to_completion(max_ticks=300)
        assert len(model._serving_programs) == n_progs

    def test_v1_scope_guards(self):
        model, params, draft, dparams = _models()
        # sampler knobs the greedy round would ignore: rejected loudly
        with pytest.raises(NotImplementedError, match="min_new_tokens"):
            PagedSpeculativeBatchingEngine(
                model, params, draft, dparams, max_slots=2, max_len=48,
                prompt_buckets=[8], block_size=4, min_new_tokens=2)
        # the CONTIGUOUS spec engine still rejects chunked prefill (its
        # step has no paged filler machinery); the paged composition
        # supports it (TestPagedSpecChunked)
        with pytest.raises(NotImplementedError, match="prefill_chunk"):
            SpeculativeBatchingEngine(
                model, params, draft, dparams, max_slots=2, max_len=48,
                prompt_buckets=[8], prefill_chunk=4)


class TestPagedSpecFuzz:
    @pytest.mark.slow
    @pytest.mark.parametrize("seed", [0, 3, 6])
    def test_random_schedules_match_solo(self, seed):
        """Randomized paged-speculative schedules: random draft_k, block
        size, pool size (down to the deferral regime), prompts, budgets,
        and staggered admission — every request equals solo greedy and
        the pool drains to zero."""
        model, params, draft, dparams = _models()
        rng = np.random.RandomState(100 + seed)
        K = int(rng.choice([1, 2, 4]))
        bs = int(rng.choice([4, 8]))
        worst = -(-(16 + 11 + K - 1) // bs)
        nb = int(rng.randint(worst, worst * 3))
        eng = PagedSpeculativeBatchingEngine(
            model, params, draft, dparams,
            max_slots=int(rng.randint(1, 4)), max_len=48, draft_k=K,
            prompt_buckets=[8, 16], block_size=bs, num_blocks=nb)
        reqs = []
        for _ in range(int(rng.randint(3, 8))):
            p = [int(t) for t in rng.randint(1, 97, rng.randint(1, 15))]
            n = int(rng.randint(1, 12))
            reqs.append((eng.add_request(p, n), p, n))
            for _ in range(int(rng.randint(0, 3))):
                eng.step()
        got = eng.run_to_completion(max_ticks=1000)
        for rid, p, n in reqs:
            assert got[rid] == _solo(model, params, p, n), \
                (seed, K, bs, nb, rid)
        assert eng.blocks_in_use == 0


class TestPagedSpecPrefixCache:
    def test_identical_prompt_hit_lossless_same_rounds(self):
        """Prefix caching composes with speculation: shared tables mean a
        cached prompt block holds BOTH models' k/v, so a hit is lossless
        AND keeps the same acceptance schedule (equal round counts cold
        vs warm — the cached DRAFT prefix must be right, not just the
        target's)."""
        model, params, draft, dparams = _models()
        eng = PagedSpeculativeBatchingEngine(
            model, params, draft, dparams, max_slots=2, max_len=48,
            draft_k=2, prompt_buckets=[16], block_size=4,
            enable_prefix_cache=True)
        LONG = list(range(3, 17))
        r0 = eng.add_request(LONG, 8)
        g0 = eng.run_to_completion(max_ticks=200)
        cold = eng.rounds
        r1 = eng.add_request(LONG, 8)
        g1 = eng.run_to_completion(max_ticks=200)
        want = _solo(model, params, LONG, 8)
        assert g0[r0] == want and g1[r1] == want
        assert eng.prefix_hits == 1 and eng.prefix_blocks_reused == 3
        assert eng.rounds == 2 * cold

    def test_concurrent_sharing_with_speculation(self):
        """Two same-prefix requests decode speculatively side by side with
        refcounted shared blocks; both stay exact."""
        model, params, draft, dparams = _models()
        eng = PagedSpeculativeBatchingEngine(
            model, params, draft, dparams, max_slots=2, max_len=48,
            draft_k=2, prompt_buckets=[16], block_size=4,
            enable_prefix_cache=True)
        a = [7] * 2 + list(range(20, 32))
        b = a[:8] + list(range(70, 76))         # same length, shared 8
        r0 = eng.add_request(a, 6)
        eng.step()                              # a admitted + decoding
        r1 = eng.add_request(b, 10)
        got = eng.run_to_completion(max_ticks=300)
        assert got[r0] == _solo(model, params, a, 6)
        assert got[r1] == _solo(model, params, b, 10)
        assert eng.prefix_hits == 1 and eng.prefix_blocks_reused == 2

    def test_int8_dual_pool_prefix(self):
        model, params, draft, dparams = _models(kv="int8")
        eng = PagedSpeculativeBatchingEngine(
            model, params, draft, dparams, max_slots=2, max_len=48,
            draft_k=2, prompt_buckets=[16], block_size=8,
            enable_prefix_cache=True)
        LONG = list(range(3, 17))
        r0 = eng.add_request(LONG, 6)
        eng.run_to_completion(max_ticks=200)
        r1 = eng.add_request(LONG, 6)
        got = eng.run_to_completion(max_ticks=200)
        assert eng.prefix_hits == 1
        assert got[r1] == _solo(model, params, LONG, 6)


class TestPagedSpecChunked:
    def test_chunked_fill_under_speculative_decode(self):
        """A long prompt chunk-fills over 4 rounds while another request
        decodes SPECULATIVELY next door — the filler's parked clock must
        keep the K+1-wide stale writes in trash; both outputs lossless."""
        model, params, draft, dparams = _models()
        eng = PagedSpeculativeBatchingEngine(
            model, params, draft, dparams, max_slots=2, max_len=64,
            draft_k=2, prompt_buckets=[4, 16], block_size=4,
            prefill_chunk=4)
        r0 = eng.add_request([40, 2], 20)      # bucket 4: decodes all test
        LONG = list(range(3, 19))              # bucket 16, pad 0: 4 segs
        r1 = eng.add_request(LONG, 8)
        got = eng.run_to_completion(max_ticks=300)
        assert got[r0] == _solo(model, params, [40, 2], 20)
        assert got[r1] == _solo(model, params, LONG, 8)

    def test_chunked_plus_prefix_plus_speculation(self):
        """All three compose: a warm prefix hit whose suffix fits one
        chunk bypasses chunked admission entirely, stays lossless, and
        keeps the acceptance schedule."""
        model, params, draft, dparams = _models()
        eng = PagedSpeculativeBatchingEngine(
            model, params, draft, dparams, max_slots=2, max_len=64,
            draft_k=2, prompt_buckets=[16], block_size=4,
            prefill_chunk=4, enable_prefix_cache=True)
        LONG = list(range(3, 17))
        r0 = eng.add_request(LONG, 8)
        g0 = eng.run_to_completion(max_ticks=300)
        cold = eng.rounds
        r1 = eng.add_request(LONG, 8)
        g1 = eng.run_to_completion(max_ticks=300)
        want = _solo(model, params, LONG, 8)
        assert g0[r0] == want and g1[r1] == want
        assert eng.prefix_hits == 1
        assert eng.rounds == 2 * cold


class TestCancel:
    """Engine.cancel(rid) on the composed speculative+paged engine
    (ISSUE 9): the shared-table allocator releases BOTH pools' blocks
    through one cancel, and the remaining request stays bit-lossless."""

    def test_cancel_releases_shared_tables(self):
        model, params, draft, dparams = _models()
        from paddle_tpu.serving import PagedSpeculativeBatchingEngine
        eng = PagedSpeculativeBatchingEngine(
            model, params, draft, dparams, max_slots=2, max_len=64,
            draft_k=2, prompt_buckets=[8], block_size=4)
        sig = []
        r0 = eng.add_request([5, 17, 3], 20,
                             on_token=lambda r, t, d: sig.append((t, d)))
        r1 = eng.add_request([40, 2], 6)
        eng.step()
        assert eng.cancel(r0)                  # active mid-spec-round
        assert sig[-1] == (None, True)
        got = eng.run_to_completion(max_ticks=200)
        assert sorted(got) == [r1]
        assert got[r1] == _solo(model, params, [40, 2], 6)
        assert eng.blocks_in_use == 0
        m = eng.metrics()
        assert m["requests_cancelled"] == 1
        assert m["blocks_allocated"] == m["blocks_released"]

    def test_cancel_contiguous_speculative(self):
        """The plain (contiguous) speculative engine cancels clean too —
        base-class slot release, no allocator involved."""
        model, params, draft, dparams = _models()
        eng = SpeculativeBatchingEngine(
            model, params, draft, dparams, max_slots=2, max_len=64,
            draft_k=2, prompt_buckets=[8])
        r0 = eng.add_request([5, 17, 3], 20)
        r1 = eng.add_request([61], 8)
        eng.step()
        assert eng.cancel(r0)
        got = eng.run_to_completion(max_ticks=200)
        assert sorted(got) == [r1]
        assert got[r1] == _solo(model, params, [61], 8)
        assert eng.metrics()["requests_cancelled"] == 1
