"""Gateway resilience layer (paddle_tpu/gateway.py ResiliencePolicy,
ISSUE 12): circuit-breaker open/half-open/close lifecycle, bounded retry
with backoff and a structured exhaustion terminal, hedge winner/loser
token-exactness, brownout ladder hysteresis (no flapping), step()
exception isolation, the autoscaler's breaker-open scale signal, the
chaos acceptance pin, and off-path purity (resilience at defaults
changes no program-cache keys and no outputs).

Control-plane tests run on the fake clock with SimEngines (no JAX);
only the purity pin builds a real tiny GPT engine."""

import json
import urllib.request

import pytest

from paddle_tpu.autoscaler import ElasticAutoscaler
from paddle_tpu.faults import Fault, FaultPlan, FaultyEngine
from paddle_tpu.gateway import (BROWNOUT_LEVELS, Brownout, CircuitBreaker,
                                ResiliencePolicy, RetriesExhausted,
                                ServingGateway)
from paddle_tpu.simulation import (SimClock, SimEngine, SimTracer,
                                   sim_tokens)


def _gw(clock, pol, **kw):
    kw.setdefault("stall_threshold_s", 60.0)
    tracer = SimTracer(clock, capacity=16384)
    return ServingGateway(clock=clock, tracer=tracer, resilience=pol,
                          **kw), tracer


def _drive(gw, clock, max_ticks=600, dt=0.25, autoscaler=None):
    for _ in range(max_ticks):
        gw.step()
        if autoscaler is not None:
            autoscaler.evaluate()
        clock.advance(dt)
        if not gw.pending():
            return
    raise AssertionError("gateway did not drain")


class TestCircuitBreakerUnit:
    def test_lifecycle_closed_open_half_open_closed(self):
        cb = CircuitBreaker(failures_to_open=2, open_s=5.0)
        assert cb.allow(0.0) and cb.state == "closed"
        assert not cb.record_failure(1.0)
        assert cb.record_failure(1.5) and cb.state == "open"
        assert not cb.allow(2.0)                  # window running
        assert cb.allow(6.6)                      # -> half_open
        assert cb.state == "half_open"
        cb.note_dispatch(6.6)
        assert not cb.allow(6.7)                  # one probe at a time
        assert cb.record_success() and cb.state == "closed"

    def test_half_open_failure_reopens(self):
        cb = CircuitBreaker(failures_to_open=1, open_s=2.0)
        cb.record_failure(0.0)
        assert cb.allow(2.5) and cb.state == "half_open"
        cb.note_dispatch(2.5)
        assert cb.record_failure(2.6) and cb.state == "open"
        assert not cb.allow(3.0)                  # window re-armed
        assert cb.allow(4.7)

    def test_validation(self):
        with pytest.raises(ValueError):
            CircuitBreaker(failures_to_open=0)
        with pytest.raises(ValueError):
            CircuitBreaker(open_s=0.0)


class TestBreakerIntegration:
    def test_open_excludes_half_open_probes_close_recloses(self):
        """The full loop against a flaky replica: consecutive dispatch
        failures open the breaker (routing excludes it, event emitted),
        the window elapses into a half-open probe, the probe succeeds
        and the breaker closes."""
        clock = SimClock()
        pol = ResiliencePolicy(breaker_failures=2, breaker_open_s=2.0,
                               retry_budget=5, retry_backoff_s=0.0,
                               retry_jitter=0.0, hedge=False,
                               brownout=False)
        gw, tracer = _gw(clock, pol)
        flaky = SimEngine(max_slots=4, tracer=SimTracer(clock))
        gw.add_replica(flaky, "flaky")
        flaky.flaky(2)
        h1 = gw.submit([1, 2], 3)
        gw.step()                                  # fail 1 -> retry
        clock.advance(0.25)
        gw.step()                                  # fail 2 -> OPEN
        assert gw.breakers_open() == ["flaky"]
        snap = gw.resilience_snapshot()
        assert snap["breakers"]["flaky"]["state"] == "open"
        # while open: nothing is routed there, the request waits
        clock.advance(0.5)
        gw.step()
        assert h1.status == "queued"
        # window elapses -> half-open probe dispatch -> success -> closed
        clock.advance(2.0)
        _drive(gw, clock)
        assert h1.status == "finished"
        assert h1.tokens == sim_tokens([1, 2], 3)
        assert gw.breakers_open() == []
        whats = [e["what"] for e in tracer.events("resilience")]
        assert "breaker_open" in whats
        assert "breaker_half_open" in whats
        assert "breaker_close" in whats
        assert whats.index("breaker_open") < whats.index("breaker_half_open") \
            < whats.index("breaker_close")

    def test_cancelled_probe_releases_half_open_claim(self):
        """Regression: a HALF_OPEN probe request cancelled before its
        first token must release the probe claim — the replica must not
        be silently excluded from routing forever."""
        clock = SimClock()
        pol = ResiliencePolicy(breaker_failures=1, breaker_open_s=1.0,
                               retry_budget=0, hedge=False,
                               brownout=False)
        gw, _ = _gw(clock, pol)
        eng = SimEngine(max_slots=4, tracer=SimTracer(clock))
        gw.add_replica(eng, "a")
        eng.flaky(1)
        probe_victim = gw.submit([1], 2)
        gw.step()                                  # fail -> OPEN, terminal
        assert probe_victim.status == "failed"
        clock.advance(1.5)                         # window elapses
        h_probe = gw.submit([2], 8)
        gw.step()                                  # HALF_OPEN probe claim
        assert h_probe.status == "dispatched"
        assert gw.cancel(h_probe.gid)              # cancel BEFORE a token
        assert h_probe.status == "cancelled"
        # the claim is free: the next request probes and closes the loop
        h_next = gw.submit([3], 2)
        _drive(gw, clock)
        assert h_next.status == "finished"
        assert h_next.tokens == sim_tokens([3], 2)
        assert gw.breakers_open() == []

    def test_stale_open_breaker_expires_from_the_scale_signal(self):
        """Regression: a breaker opened at the END of a burst (no
        further traffic ever routes, so allow() is never called again)
        must fall out of breakers_open() once its window elapses — a
        stale signal would pin an idle autoscaled fleet at max size
        forever."""
        clock = SimClock()
        pol = ResiliencePolicy(breaker_failures=1, breaker_open_s=1.0,
                               retry_budget=0, hedge=False,
                               brownout=False)
        gw, _ = _gw(clock, pol)
        eng = SimEngine(max_slots=4, tracer=SimTracer(clock))
        gw.add_replica(eng, "a")
        eng.flaky(1)
        gw.submit([1], 2)
        gw.step()                                  # fail -> OPEN
        assert gw.breakers_open() == ["a"]
        clock.advance(100.0)                       # traffic long gone
        gw.step()
        assert gw.breakers_open() == []            # window elapsed
        # the raw state is still visible to operators, honestly labeled
        assert gw.resilience_snapshot()["breakers"]["a"]["state"] == "open"

    def test_unrelated_cancel_does_not_touch_the_probe_claim(self):
        """Regression: the HALF_OPEN probe verdict is keyed to the probe
        REQUEST — cancelling a pre-open in-flight request token-lessly
        must not free the claim (a second request would join the
        half-open replica while the true probe still races)."""
        clock = SimClock()
        pol = ResiliencePolicy(breaker_failures=1, breaker_open_s=1.0,
                               retry_budget=0, hedge=False,
                               brownout=False)
        gw, _ = _gw(clock, pol)
        eng = SimEngine(max_slots=8, tracer=SimTracer(clock))
        gw.add_replica(eng, "a")
        eng.stall(10 ** 6)              # park the engine: no tokens move
        q_old = gw.submit([1], 50)                 # dispatched while CLOSED
        gw.step()
        assert q_old.status == "dispatched"
        assert q_old.first_token_at is None        # token-less, pre-open
        eng.flaky(1)
        gw.submit([2], 2)
        gw.step()                                  # fail -> OPEN
        clock.advance(1.5)                         # window elapses
        probe = gw.submit([3], 2)
        gw.step()                                  # HALF_OPEN, P claimed
        assert probe.status == "dispatched"
        assert gw.cancel(q_old.gid)                # unrelated, pre-open
        waiting = gw.submit([4], 2)
        gw.step()
        # the claim is still the probe's: nothing else joins the replica
        assert waiting.status == "queued"
        cb = gw.resilience_snapshot()["breakers"]["a"]
        assert cb["state"] == "half_open"
        eng.stall(0)                               # un-park: probe lands
        _drive(gw, clock)
        assert probe.status == "finished"
        assert waiting.status == "finished"
        assert gw.breakers_open() == []

    def test_expired_probe_reopens_breaker(self):
        """A probe that blows its TTFT deadline without a token IS the
        probe's verdict: the breaker re-opens."""
        clock = SimClock()
        pol = ResiliencePolicy(breaker_failures=1, breaker_open_s=1.0,
                               retry_budget=0, hedge=False,
                               brownout=False)
        gw, _ = _gw(clock, pol, stall_threshold_s=1e9)
        eng = SimEngine(max_slots=4, tracer=SimTracer(clock))
        gw.add_replica(eng, "a")
        eng.flaky(1)
        gw.submit([1], 2)
        gw.step()                                  # fail -> OPEN
        clock.advance(1.5)
        eng.stall(10 ** 6)                         # wedged: no tokens ever
        h = gw.submit([2], 2, ttft_deadline_s=2.0)
        gw.step()                                  # half-open probe
        assert h.status == "dispatched"
        clock.advance(3.0)
        gw.step()                                  # ttft expiry fires
        assert h.status == "expired"
        assert gw.breakers_open() == ["a"]         # re-opened, re-armed

    def test_quarantine_counts_as_breaker_failure(self):
        clock = SimClock()
        pol = ResiliencePolicy(breaker_failures=1, breaker_open_s=100.0,
                               hedge=False, brownout=False)
        gw, _ = _gw(clock, pol)
        gw.add_replica(SimEngine(max_slots=2, tracer=SimTracer(clock)),
                       "a")
        gw.quarantine("a", reason="operator")
        # the breaker opened, but a QUARANTINED replica is not a
        # scale-up signal (its missing capacity belongs to the
        # quarantine-reap/min-bound machinery — an open breaker on a
        # benched shell could never half-open and would page forever)
        assert gw.resilience_snapshot()["breakers"]["a"]["state"] == "open"
        assert gw.breakers_open() == []
        # reinstate returns it to rotation: NOW it counts, and the
        # breaker still gates dispatch until a half-open probe succeeds
        gw.reinstate("a")
        assert gw.replica("a").state == "active"
        assert gw.breakers_open() == ["a"]


class TestRetry:
    def test_budget_exhaustion_is_structured_terminal(self):
        clock = SimClock()
        pol = ResiliencePolicy(retry_budget=2, retry_backoff_s=0.1,
                               retry_jitter=0.0, breaker_failures=100,
                               hedge=False, brownout=False)
        gw, tracer = _gw(clock, pol)
        eng = SimEngine(max_slots=2, tracer=SimTracer(clock))
        gw.add_replica(eng, "a")
        eng.flaky(100)                            # never recovers
        sig = []
        h = gw.submit([1], 2, on_token=lambda g, t, d: sig.append((t, d)))
        for _ in range(40):
            gw.step()
            clock.advance(0.25)
            if h.done:
                break
        assert h.status == "failed"
        assert isinstance(h.error, RetriesExhausted)
        assert h.error.attempts == 3 and h.error.budget == 2
        assert h.retries == 2                     # never beyond budget
        assert sig == [(None, True)]              # terminal, never silent
        whats = [e["what"] for e in tracer.events("resilience")]
        assert whats.count("retry") == 2
        assert whats.count("retries_exhausted") == 1

    def test_backoff_is_exponential_capped_and_seeded(self):
        pol = ResiliencePolicy(retry_backoff_s=0.1, retry_backoff_max_s=0.5,
                               retry_jitter=0.0, seed=0)
        import random
        rng = random.Random(0)
        assert [pol.backoff_s(a, rng) for a in (1, 2, 3, 4, 5)] == \
            [0.1, 0.2, 0.4, 0.5, 0.5]
        # jitter draws come from the gateway's seeded RNG: same seed,
        # same schedule
        polj = ResiliencePolicy(retry_backoff_s=0.1, retry_jitter=0.5,
                                seed=7)
        a = [polj.backoff_s(i, random.Random(7)) for i in (1, 2)]
        b = [polj.backoff_s(i, random.Random(7)) for i in (1, 2)]
        assert a == b
        lo, hi = 0.1 * 0.5, 0.1 * 1.5
        assert lo <= polj.backoff_s(1, random.Random(1)) <= hi

    def test_backoff_defers_without_blocking_the_queue(self):
        """A backing-off request must not head-of-line block: requests
        behind it dispatch while it waits out ``not_before``."""
        clock = SimClock()
        pol = ResiliencePolicy(retry_budget=3, retry_backoff_s=5.0,
                               retry_jitter=0.0, breaker_failures=100,
                               hedge=False, brownout=False)
        gw, _ = _gw(clock, pol)
        eng = SimEngine(max_slots=4, tracer=SimTracer(clock))
        gw.add_replica(eng, "a")
        eng.flaky(1)
        h_retry = gw.submit([1], 2)               # eats the flaky failure
        h_next = gw.submit([2], 2)
        gw.step()
        assert h_retry.status == "queued" and h_retry.not_before > clock()
        assert h_next.status in ("dispatched", "finished")
        _drive(gw, clock)
        assert h_retry.status == "finished" and h_next.status == "finished"


class TestHedge:
    def _straggler_fleet(self, clock, pol, factor=40):
        gw, tracer = _gw(clock, pol)
        slow = SimEngine(max_slots=4, tracer=SimTracer(clock))
        fast = SimEngine(max_slots=4, tracer=SimTracer(clock))
        plan = FaultPlan([Fault("slow", at_s=0.0, factor=factor)])
        gw.add_replica(FaultyEngine(slow, plan, clock, replica="slow"),
                       "slow")
        gw.add_replica(fast, "fast")
        return gw, tracer, slow, fast

    def test_winner_token_exactness_loser_cancelled(self):
        """The hedge races a straggler: the fast replica's first token
        wins, the loser attempt is cancelled on its engine, and the
        consumer stream is exactly the oracle — no duplicates, no
        interleaving."""
        clock = SimClock()
        pol = ResiliencePolicy(hedge=True, hedge_ttft_frac=0.2,
                               max_hedges=4, brownout=False)
        gw, tracer, slow, fast = self._straggler_fleet(clock, pol)
        streams = {}
        h = gw.submit([9, 9], 6, ttft_deadline_s=5.0,
                      on_token=lambda g, t, d:
                      streams.setdefault(g, []).append((t, d)))
        _drive(gw, clock)
        assert h.status == "finished" and h.hedged
        assert h.replica == "fast"                # hedge won
        assert h.tokens == sim_tokens([9, 9], 6)
        toks = [t for t, d in streams[h.gid] if t is not None]
        assert toks == h.tokens                   # single-sourced stream
        assert streams[h.gid][-1] == (h.tokens[-1], True)
        assert slow.metrics()["requests_cancelled"] == 1    # the loser
        counters = gw.resilience_snapshot()["counters"]
        assert counters["hedges"] == 1 and counters["hedges_won"] == 1
        assert gw.resilience_snapshot()["hedges_inflight"] == 0
        whats = [e["what"] for e in tracer.events("resilience")]
        assert whats == ["hedge", "hedge_won"]

    def test_primary_win_counts_hedge_lost(self):
        """A hedge fired against a replica that delivers after all: the
        primary's token wins, the hedge attempt is the cancelled loser."""
        clock = SimClock()
        pol = ResiliencePolicy(hedge=True, hedge_ttft_frac=0.2,
                               max_hedges=4, brownout=False)
        # mild straggler: slower than the hedge trigger, faster than the
        # hedge's own queue+prefill on the other replica is NOT possible
        # in the sim (both serve next tick), so force the primary win by
        # making the hedge target slow instead
        gw, tracer = _gw(clock, pol)
        primary = SimEngine(max_slots=4, tracer=SimTracer(clock))
        laggard = SimEngine(max_slots=4, tracer=SimTracer(clock))
        plan = FaultPlan([Fault("slow", at_s=0.0, factor=13)])
        gw.add_replica(FaultyEngine(primary, plan, clock, replica="p"),
                       "p")
        gw.add_replica(FaultyEngine(laggard, plan, clock, replica="h"),
                       "h")
        h = gw.submit([4, 2], 3, ttft_deadline_s=4.0)
        _drive(gw, clock)
        assert h.status == "finished" and h.hedged
        assert h.tokens == sim_tokens([4, 2], 3)
        counters = gw.resilience_snapshot()["counters"]
        assert counters["hedges"] == 1
        assert counters.get("hedges_won", 0) + \
            counters.get("hedges_lost", 0) == 1

    def test_hedge_budget_bounds_concurrency(self):
        clock = SimClock()
        pol = ResiliencePolicy(hedge=True, hedge_ttft_frac=0.1,
                               max_hedges=1, brownout=False)
        gw, _, slow, fast = self._straggler_fleet(clock, pol, factor=400)
        hs = [gw.submit([i + 1], 4, ttft_deadline_s=8.0)
              for i in range(4)]
        peak = 0
        for _ in range(200):
            gw.step()
            peak = max(peak, gw.resilience_snapshot()["hedges_inflight"])
            clock.advance(0.25)
            if not gw.pending():
                break
        assert peak <= 1
        for h in hs:
            assert h.status == "finished"
            assert h.tokens == sim_tokens(h.prompt, 4)

    def test_no_hedge_without_ttft_deadline(self):
        clock = SimClock()
        pol = ResiliencePolicy(hedge=True, hedge_ttft_frac=0.1,
                               brownout=False)
        gw, _, slow, fast = self._straggler_fleet(clock, pol, factor=10)
        h = gw.submit([5], 3)                     # no deadline: no hedge
        _drive(gw, clock)
        assert h.status == "finished" and not h.hedged
        assert gw.resilience_snapshot()["counters"].get("hedges", 0) == 0

    def test_quarantined_primary_promotes_hedge_twin(self):
        """Quarantine hits the primary replica while a hedge is racing:
        only that attempt is dropped — the hedge twin carries the
        request to completion, no re-queue, no replay signal."""
        clock = SimClock()
        pol = ResiliencePolicy(hedge=True, hedge_ttft_frac=0.05,
                               max_hedges=4, brownout=False)
        gw, tracer = _gw(clock, pol, stall_threshold_s=4.0)
        plan = FaultPlan([Fault("crash", at_s=1.0, replica="dead")])
        dead = SimEngine(max_slots=4, tracer=SimTracer(clock))
        gw.add_replica(FaultyEngine(dead, plan, clock, replica="dead"),
                       "dead")
        fast = SimEngine(max_slots=4, tracer=SimTracer(clock))
        gw.add_replica(fast, "fast")
        # occupy fast so routing sends the victim to dead first
        fillers = [gw.submit([40 + i], 2) for i in range(2)]
        gw.step()
        victim = gw.submit([8, 8], 50, ttft_deadline_s=60.0)
        _drive(gw, clock)
        assert victim.status == "finished"
        assert victim.tokens == sim_tokens([8, 8], 50)
        for f in fillers:
            assert f.status == "finished"


class TestBrownout:
    def _pol(self, **kw):
        kw.setdefault("hedge", False)
        kw.setdefault("brownout", True)
        kw.setdefault("brownout_high", 2.0)
        kw.setdefault("brownout_low", 0.5)
        kw.setdefault("brownout_up_dwell_s", 0.0)
        kw.setdefault("brownout_down_dwell_s", 1.0)
        kw.setdefault("brownout_clamp", 3)
        kw.setdefault("brownout_use_slo", False)
        return ResiliencePolicy(**kw)

    def test_ladder_up_clamp_priority_shed_then_down(self):
        clock = SimClock()
        gw, tracer = _gw(clock, self._pol(), max_queue_depth=1000)
        gw.add_replica(SimEngine(max_slots=2), "a")
        hs = [gw.submit([i + 1], 9, priority=1) for i in range(12)]
        gw.step()                                  # pressure 6 -> clamp
        assert gw.brownout_level == 1
        gw.step()                                  # -> priority_only
        assert gw.brownout_level == 2
        low = gw.submit([50], 4, priority=1)
        hi = gw.submit([51], 4, priority=0)
        assert low.status == "shed"
        assert isinstance(low.error, Brownout)
        assert low.error.label == "priority_only" and low.error.level == 2
        assert hi.status == "queued"
        gw.step()                                  # -> shed_all
        assert gw.brownout_level == 3
        any_pri = gw.submit([52], 4, priority=0)
        assert any_pri.status == "shed"
        assert any_pri.error.label == "shed_all"
        # drain, keep stepping after idle: ladder walks back down
        _drive(gw, clock)
        for _ in range(40):
            gw.step()
            clock.advance(0.25)
        assert gw.brownout_level == 0
        # clamp pinned: every dispatched request got at most clamp tokens
        for h in hs:
            if h.status == "finished":
                assert len(h.tokens) <= 3
                assert h.tokens == sim_tokens(h.prompt, len(h.tokens))
        whats = [e["what"] for e in tracer.events("resilience")]
        assert whats.count("brownout_up") == 3
        assert whats.count("brownout_down") == 3

    def test_hysteresis_band_holds_no_flapping(self):
        """The ladder state machine pin: pressure parked INSIDE the
        (low, high) band neither climbs nor descends — however long it
        hovers — and dwell timers reset when pressure re-enters the
        band, so a value oscillating across one threshold cannot flap
        the rung."""
        from paddle_tpu.gateway import _BrownoutLadder
        lad = _BrownoutLadder(self._pol(brownout_high=2.0,
                                        brownout_low=0.5,
                                        brownout_up_dwell_s=0.0,
                                        brownout_down_dwell_s=1.0))
        assert lad.evaluate(0.0, 5.0, False) == +1      # climb
        assert lad.level == 1
        # hover inside the band for a long time: rung holds, forever
        for i in range(1, 200):
            assert lad.evaluate(float(i), 1.0, False) == 0
            assert lad.level == 1
        # oscillate across the LOW threshold: each re-entry into the
        # band resets the descend dwell, so the rung still holds
        t = 200.0
        for _ in range(20):
            assert lad.evaluate(t, 0.4, False) == 0     # below, dwell on
            assert lad.evaluate(t + 0.5, 1.0, False) == 0   # back in band
            t += 1.0
        assert lad.level == 1
        # sustained below-low finally descends after the dwell
        assert lad.evaluate(t, 0.4, False) == 0
        assert lad.evaluate(t + 1.1, 0.4, False) == -1
        assert lad.level == 0
        # and it never descends below the floor / climbs past the top
        assert lad.evaluate(t + 3.0, 0.0, False) == 0
        for i in range(10):
            lad.evaluate(t + 4.0 + i, 99.0, False)
        assert lad.level == len(BROWNOUT_LEVELS) - 1
        assert lad.evaluate(t + 30.0, 99.0, False) == 0

    def test_slo_firing_climbs_ladder(self):
        class FiringSLO:
            def alert_states(self):
                return {"ttft_p99": "firing"}

            def count(self, *_a, **_k):
                pass

            def observe(self, *_a, **_k):
                pass
        clock = SimClock()
        gw, _ = _gw(clock, self._pol(brownout_use_slo=True,
                                     brownout_up_dwell_s=0.5))
        gw.add_replica(SimEngine(max_slots=2), "a")
        gw.set_slo(FiringSLO())
        gw.step()                     # dwell starts (occupancy is 0!)
        assert gw.brownout_level == 0
        clock.advance(1.0)
        gw.step()
        assert gw.brownout_level == 1


class TestStepIsolation:
    def test_raising_engine_quarantined_others_untouched(self):
        """The satellite regression: an engine raising mid-tick must
        quarantine THAT replica and replay its in-flight work — the
        other replica's requests in the same gateway tick proceed."""
        clock = SimClock()
        tracer = SimTracer(clock, capacity=8192)
        gw = ServingGateway(clock=clock, tracer=tracer,
                            stall_threshold_s=60.0)   # resilience OFF
        plan = FaultPlan([Fault("garble", at_s=0.0, count=1)])
        bad = SimEngine(max_slots=2, tracer=SimTracer(clock))
        ok = SimEngine(max_slots=2, tracer=SimTracer(clock))
        gw.add_replica(FaultyEngine(bad, plan, clock, replica="bad"),
                       "bad")
        gw.add_replica(ok, "ok")
        hs = [gw.submit([i + 3, 1], 6) for i in range(4)]
        for _ in range(100):
            gw.step()                 # must never raise
            clock.advance(0.25)
            if not gw.pending():
                break
        assert gw.replica("bad").state == "quarantined"
        assert "step raised" in gw.replica("bad").reason
        for h in hs:
            assert h.status == "finished"
            assert h.tokens == sim_tokens(h.prompt, 6)
        assert gw.metrics()["step_errors"] == 1
        assert any(e["what"] == "replica_step_error"
                   for e in tracer.events("gateway"))

    def test_serving_engine_step_surfaces_errors(self):
        """serving.py satellite: a raising _step_impl ticks the
        step_errors counter and emits an engine_error event before the
        exception propagates."""
        import paddle_tpu as paddle
        from paddle_tpu.models.gpt import GPTConfig, GPTModel
        from paddle_tpu.serving import ContinuousBatchingEngine
        from paddle_tpu.telemetry import Tracer
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_attention_heads=2, max_position_embeddings=64,
                        compute_dtype="float32")
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        tr = Tracer()
        eng = ContinuousBatchingEngine(model, params, max_slots=2,
                                       max_len=32, prompt_buckets=[8],
                                       tracer=tr)
        eng._step_impl = lambda: (_ for _ in ()).throw(
            RuntimeError("boom"))
        with pytest.raises(RuntimeError):
            eng.step()
        assert eng.metrics()["step_errors"] == 1
        assert "step_errors" in type(eng).metrics_schema()
        evs = tr.events("engine_error")
        assert evs and evs[-1]["what"] == "step_error"
        assert "boom" in evs[-1]["error"]


class TestAutoscalerBreakerSignal:
    def test_breaker_open_drives_scale_up(self):
        clock = SimClock()
        tracer = SimTracer(clock, capacity=8192)
        pol = ResiliencePolicy(breaker_failures=2, breaker_open_s=100.0,
                               retry_budget=5, retry_backoff_s=0.0,
                               retry_jitter=0.0, hedge=False,
                               brownout=False)
        gw = ServingGateway(clock=clock, tracer=tracer, resilience=pol)
        eng = SimEngine(max_slots=2, tracer=SimTracer(clock))
        gw.add_replica(eng, "r0")
        eng.flaky(50)
        asc = ElasticAutoscaler(
            gw, lambda: SimEngine(max_slots=2, tracer=SimTracer(clock)),
            min_replicas=1, max_replicas=3, scale_up_cooldown_s=1.0,
            tracer=tracer, clock=clock)
        h = gw.submit([4, 4], 3)
        for _ in range(30):
            gw.step()
            asc.evaluate()
            clock.advance(0.25)
            if h.done:
                break
        assert h.status == "finished"             # served by the spawn
        ups = [d for d in asc.decisions() if d["action"] == "scale_up"]
        assert ups and "breaker:r0" in ups[0]["reason"]
        assert asc.breakers_open() == ["r0"]
        snap = asc.autoscaler_snapshot()
        assert snap["signals"]["breakers_open"] == ["r0"]

    def test_gateway_without_resilience_reports_no_breakers(self):
        clock = SimClock()
        gw = ServingGateway(clock=clock)
        asc = ElasticAutoscaler(gw, lambda: SimEngine(), clock=clock)
        assert asc.breakers_open() == []


class TestChaosAcceptance:
    def test_seeded_plan_pin(self):
        """The ISSUE 12 acceptance pin, via the bench config itself
        (single source of truth): replica death mid-burst + stall + slow
        straggler + transient dispatch errors under a seeded plan —
        resilience-on delivers every admitted request a terminal
        outcome, keeps retries within budget, and strictly beats
        resilience-off on p99 TTFT.  The bench function asserts all of
        that internally; the record's A/B numbers are re-checked here."""
        import bench
        rec = bench.bench_gpt_chaos(False)
        chaos = rec["chaos"]
        on, off = chaos["resilience_on"], chaos["resilience_off"]
        assert on["ttft_s_p99"] < off["ttft_s_p99"]
        assert chaos["p99_ttft_improvement"] > 1.0
        assert on["outcomes"]["finished"] >= off["outcomes"]["finished"]
        assert sum(on["outcomes"].values()) == on["offered"]
        assert chaos["counters"].get("retries_exhausted", 0) <= 1
        assert rec["decisions"]                    # the decision timeline
        assert chaos["plan"]["faults"]             # the plan rides along


class TestObservability:
    def test_ops_resilience_route_and_404(self):
        from paddle_tpu.ops_server import OpsServer
        clock = SimClock()
        pol = ResiliencePolicy(brownout=False, hedge=False)
        gw, _ = _gw(clock, pol)
        gw.add_replica(SimEngine(max_slots=2), "a")
        srv = OpsServer()
        srv.attach(gw, "gw")
        url = srv.start()
        try:
            snap = json.loads(urllib.request.urlopen(
                url + "/resilience", timeout=10).read())
            assert snap["breakers"]["a"]["state"] == "closed"
            assert snap["policy"]["retry_budget"] == pol.retry_budget
            txt = urllib.request.urlopen(url + "/metrics",
                                         timeout=10).read().decode()
            assert "paddle_tpu_resilience_brownout_level 0" in txt
            assert "paddle_tpu_resilience_breakers_open 0" in txt
        finally:
            srv.stop()
        # a gateway WITHOUT a policy: /resilience is 404, not a lie
        srv2 = OpsServer()
        srv2.attach(ServingGateway(clock=clock), "bare")
        url2 = srv2.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url2 + "/resilience", timeout=10)
            assert ei.value.code == 404
        finally:
            srv2.stop()

    def test_flight_recorder_dumps_breaker_brownout_state(self, tmp_path):
        from paddle_tpu.telemetry_ledger import FlightRecorder
        clock = SimClock()
        pol = ResiliencePolicy(brownout=True, hedge=False)
        gw, _ = _gw(clock, pol)
        gw.add_replica(SimEngine(max_slots=2), "a")
        fr = FlightRecorder(str(tmp_path)).add_source(gw, "gateway")
        out = fr.dump("test")
        data = json.load(open(f"{out}/gateway.json"))
        res = data["resilience"]
        assert res["breakers"]["a"]["state"] == "closed"
        assert res["brownout"]["label"] == "normal"

    def test_retry_hedge_events_carry_trace_ids(self):
        """Resilience events for a traced request carry enough identity
        (gid) to join the request's stitched trace."""
        clock = SimClock()
        pol = ResiliencePolicy(retry_budget=2, retry_backoff_s=0.0,
                               retry_jitter=0.0, breaker_failures=100,
                               hedge=False, brownout=False)
        gw, tracer = _gw(clock, pol)
        eng = SimEngine(max_slots=2, tracer=SimTracer(clock))
        gw.add_replica(eng, "a")
        eng.flaky(1)
        h = gw.submit([1], 2)
        _drive(gw, clock)
        retries = [e for e in tracer.events("resilience")
                   if e["what"] == "retry"]
        assert retries and retries[0]["gid"] == h.gid


class TestOffPathPurity:
    @pytest.fixture(scope="class")
    def model_and_params(self):
        import paddle_tpu as paddle
        from paddle_tpu.models.gpt import GPTConfig, GPTModel
        paddle.seed(11)
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=96,
                        compute_dtype="float32")
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        return model, params

    def test_resilience_at_defaults_changes_no_programs_or_outputs(
            self, model_and_params):
        """The off-path purity pin: the same workload through a gateway
        with a ResiliencePolicy attached (no faults injected) and one
        without produces token-identical outputs from an IDENTICAL
        program-cache key population — resilience is host-side control
        flow only."""
        from paddle_tpu.serving import PagedContinuousBatchingEngine
        model, params = model_and_params
        prompts = [([5, 17, 3], 8), ([40, 2], 6), ([61], 5)]

        def run(pol):
            model.__dict__.pop("_serving_programs", None)
            eng = PagedContinuousBatchingEngine(
                model, params, max_slots=2, max_len=32, block_size=4,
                prompt_buckets=[8, 16])
            clock = SimClock()
            gw = ServingGateway(clock=clock, resilience=pol)
            gw.add_replica(eng, "a")
            handles = [gw.submit(p, n, ttft_deadline_s=1e9)
                       for p, n in prompts]
            for _ in range(300):
                gw.step()
                clock.advance(0.01)
                if not gw.pending():
                    break
            keys = set(model.__dict__["_serving_programs"])
            return [tuple(h.tokens) for h in handles], keys

        toks_off, keys_off = run(None)
        toks_on, keys_on = run(ResiliencePolicy())
        assert toks_on == toks_off
        assert keys_on == keys_off
        assert all(t for t in toks_on)
