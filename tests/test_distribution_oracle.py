"""Distribution numerics vs torch.distributions (previously surface-tested
only; ≙ reference test_distribution.py log_prob/entropy/kl checks)."""
import numpy as np
import torch

import paddle_tpu as paddle
from paddle_tpu.distribution import (Bernoulli, Categorical, Normal, Uniform,
                                     kl_divergence)


def _np(t):
    return np.asarray(t._data)


def test_normal_log_prob_entropy_kl():
    loc, scale = np.float32(0.5), np.float32(1.7)
    d = Normal(loc, scale)
    td = torch.distributions.Normal(torch.tensor(loc), torch.tensor(scale))
    x = np.linspace(-3, 3, 7).astype("float32")
    np.testing.assert_allclose(_np(d.log_prob(paddle.to_tensor(x))),
                               td.log_prob(torch.tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(_np(d.entropy())),
                               float(td.entropy()), rtol=1e-5)
    d2 = Normal(np.float32(-1.0), np.float32(0.6))
    td2 = torch.distributions.Normal(torch.tensor(-1.0), torch.tensor(0.6))
    np.testing.assert_allclose(
        float(_np(kl_divergence(d, d2))),
        float(torch.distributions.kl_divergence(td, td2)), rtol=1e-4)


def test_uniform_log_prob_entropy():
    d = Uniform(np.float32(-1.0), np.float32(3.0))
    td = torch.distributions.Uniform(torch.tensor(-1.0), torch.tensor(3.0))
    x = np.array([-0.5, 0.0, 2.9], "float32")
    np.testing.assert_allclose(_np(d.log_prob(paddle.to_tensor(x))),
                               td.log_prob(torch.tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(_np(d.entropy())),
                               float(td.entropy()), rtol=1e-5)


def test_categorical_log_prob_and_kl():
    logits = np.array([0.1, 1.2, -0.7, 0.4], "float32")
    d = Categorical(paddle.to_tensor(logits))
    td = torch.distributions.Categorical(logits=torch.tensor(logits))
    x = np.array([0, 2, 3], "int64")
    got = _np(d.log_prob(paddle.to_tensor(x)))
    np.testing.assert_allclose(got, td.log_prob(torch.tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-5)
    logits2 = np.array([1.0, 0.0, 0.0, -1.0], "float32")
    d2 = Categorical(paddle.to_tensor(logits2))
    td2 = torch.distributions.Categorical(logits=torch.tensor(logits2))
    np.testing.assert_allclose(
        float(np.asarray(getattr(kl_divergence(d, d2), "_data",
                                 kl_divergence(d, d2)))),
        float(torch.distributions.kl_divergence(td, td2)), rtol=1e-4)


def test_bernoulli_log_prob_mean_variance():
    p = np.float32(0.3)
    d = Bernoulli(p)
    td = torch.distributions.Bernoulli(torch.tensor(p))
    x = np.array([0., 1., 1.], "float32")
    np.testing.assert_allclose(_np(d.log_prob(paddle.to_tensor(x))),
                               td.log_prob(torch.tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(_np(d.mean)), 0.3, rtol=1e-6)
    np.testing.assert_allclose(float(_np(d.variance)), 0.21, rtol=1e-5)


def test_normal_sampling_moments():
    paddle.seed(7)
    d = Normal(np.float32(2.0), np.float32(0.5))
    s = _np(d.sample([20000]))
    assert abs(s.mean() - 2.0) < 0.02
    assert abs(s.std() - 0.5) < 0.02
