"""Distribution numerics vs torch.distributions (previously surface-tested
only; ≙ reference test_distribution.py log_prob/entropy/kl checks)."""
import numpy as np
import torch

import paddle_tpu as paddle
from paddle_tpu.distribution import (Bernoulli, Categorical, Normal, Uniform,
                                     kl_divergence)


def _np(t):
    return np.asarray(t._data)


def test_normal_log_prob_entropy_kl():
    loc, scale = np.float32(0.5), np.float32(1.7)
    d = Normal(loc, scale)
    td = torch.distributions.Normal(torch.tensor(loc), torch.tensor(scale))
    x = np.linspace(-3, 3, 7).astype("float32")
    np.testing.assert_allclose(_np(d.log_prob(paddle.to_tensor(x))),
                               td.log_prob(torch.tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(_np(d.entropy())),
                               float(td.entropy()), rtol=1e-5)
    d2 = Normal(np.float32(-1.0), np.float32(0.6))
    td2 = torch.distributions.Normal(torch.tensor(-1.0), torch.tensor(0.6))
    np.testing.assert_allclose(
        float(_np(kl_divergence(d, d2))),
        float(torch.distributions.kl_divergence(td, td2)), rtol=1e-4)


def test_uniform_log_prob_entropy():
    d = Uniform(np.float32(-1.0), np.float32(3.0))
    td = torch.distributions.Uniform(torch.tensor(-1.0), torch.tensor(3.0))
    x = np.array([-0.5, 0.0, 2.9], "float32")
    np.testing.assert_allclose(_np(d.log_prob(paddle.to_tensor(x))),
                               td.log_prob(torch.tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(_np(d.entropy())),
                               float(td.entropy()), rtol=1e-5)


def test_categorical_log_prob_and_kl():
    logits = np.array([0.1, 1.2, -0.7, 0.4], "float32")
    d = Categorical(paddle.to_tensor(logits))
    td = torch.distributions.Categorical(logits=torch.tensor(logits))
    x = np.array([0, 2, 3], "int64")
    got = _np(d.log_prob(paddle.to_tensor(x)))
    np.testing.assert_allclose(got, td.log_prob(torch.tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-5)
    logits2 = np.array([1.0, 0.0, 0.0, -1.0], "float32")
    d2 = Categorical(paddle.to_tensor(logits2))
    td2 = torch.distributions.Categorical(logits=torch.tensor(logits2))
    np.testing.assert_allclose(
        float(np.asarray(getattr(kl_divergence(d, d2), "_data",
                                 kl_divergence(d, d2)))),
        float(torch.distributions.kl_divergence(td, td2)), rtol=1e-4)


def test_bernoulli_log_prob_mean_variance():
    p = np.float32(0.3)
    d = Bernoulli(p)
    td = torch.distributions.Bernoulli(torch.tensor(p))
    x = np.array([0., 1., 1.], "float32")
    np.testing.assert_allclose(_np(d.log_prob(paddle.to_tensor(x))),
                               td.log_prob(torch.tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(_np(d.mean)), 0.3, rtol=1e-6)
    np.testing.assert_allclose(float(_np(d.variance)), 0.21, rtol=1e-5)


def test_normal_sampling_moments():
    paddle.seed(7)
    d = Normal(np.float32(2.0), np.float32(0.5))
    s = _np(d.sample([20000]))
    assert abs(s.mean() - 2.0) < 0.02
    assert abs(s.std() - 0.5) < 0.02


# ---------------------------------------------------------------------------
# Round-4 depth (VERDICT r3 missing #7): Beta / Dirichlet / Multinomial /
# Gamma / Laplace / LogNormal / Transformed / Independent vs torch oracles
# ---------------------------------------------------------------------------

def test_beta_log_prob_entropy_mean_var_kl():
    from paddle_tpu.distribution import Beta
    a, b = np.float32(2.5), np.float32(1.3)
    d, td = Beta(a, b), torch.distributions.Beta(torch.tensor(a),
                                                 torch.tensor(b))
    x = np.linspace(0.05, 0.95, 7).astype("float32")
    np.testing.assert_allclose(_np(d.log_prob(paddle.to_tensor(x))),
                               td.log_prob(torch.tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(_np(d.entropy())), float(td.entropy()),
                               rtol=1e-5)
    np.testing.assert_allclose(float(_np(d.mean)), float(td.mean), rtol=1e-6)
    np.testing.assert_allclose(float(_np(d.variance)), float(td.variance),
                               rtol=1e-6)
    d2 = Beta(np.float32(0.8), np.float32(2.0))
    td2 = torch.distributions.Beta(torch.tensor(0.8), torch.tensor(2.0))
    np.testing.assert_allclose(float(_np(kl_divergence(d, d2))),
                               float(torch.distributions.kl_divergence(td,
                                                                       td2)),
                               rtol=1e-4)


def test_beta_sampling_moments_and_rsample_grad():
    from paddle_tpu.distribution import Beta
    paddle.seed(11)
    d = Beta(np.float32(2.0), np.float32(5.0))
    s = _np(d.sample([40000]))
    assert abs(s.mean() - 2.0 / 7.0) < 0.01
    assert ((s > 0) & (s < 1)).all()
    # rsample itself is differentiable wrt parameters (reparameterized
    # gammas) — differentiate through the actual API, not a re-derivation
    import jax
    import jax.numpy as jnp

    def mean_sample(a):
        paddle.seed(0)  # same draws every evaluation
        return jnp.mean(Beta(a, np.float32(5.0)).rsample([512])._data)
    g = float(jax.grad(mean_sample)(jnp.float32(2.0)))
    assert g > 0  # raising alpha raises the mean


def test_dirichlet_log_prob_entropy_kl():
    from paddle_tpu.distribution import Dirichlet
    c = np.array([1.5, 2.0, 3.5], "float32")
    d = Dirichlet(c)
    td = torch.distributions.Dirichlet(torch.tensor(c))
    x = np.array([[0.2, 0.3, 0.5], [0.6, 0.1, 0.3]], "float32")
    np.testing.assert_allclose(_np(d.log_prob(paddle.to_tensor(x))),
                               td.log_prob(torch.tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(_np(d.entropy())), float(td.entropy()),
                               rtol=1e-5)
    np.testing.assert_allclose(_np(d.mean), td.mean.numpy(), rtol=1e-6)
    np.testing.assert_allclose(_np(d.variance), td.variance.numpy(),
                               rtol=1e-5)
    c2 = np.array([3.0, 1.0, 1.0], "float32")
    d2, td2 = Dirichlet(c2), torch.distributions.Dirichlet(torch.tensor(c2))
    np.testing.assert_allclose(float(_np(kl_divergence(d, d2))),
                               float(torch.distributions.kl_divergence(td,
                                                                       td2)),
                               rtol=1e-4)


def test_dirichlet_sampling_simplex():
    from paddle_tpu.distribution import Dirichlet
    paddle.seed(3)
    d = Dirichlet(np.array([2.0, 3.0, 5.0], "float32"))
    s = _np(d.sample([20000]))
    np.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-5)
    np.testing.assert_allclose(s.mean(0), [0.2, 0.3, 0.5], atol=0.01)


def test_multinomial_log_prob_mean_var_sampling():
    from paddle_tpu.distribution import Multinomial
    p = np.array([0.2, 0.3, 0.5], "float32")
    d = Multinomial(10, p)
    td = torch.distributions.Multinomial(10, probs=torch.tensor(p))
    x = np.array([[2., 3., 5.], [0., 4., 6.], [10., 0., 0.]], "float32")
    np.testing.assert_allclose(_np(d.log_prob(paddle.to_tensor(x))),
                               td.log_prob(torch.tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(_np(d.mean), td.mean.numpy(), rtol=1e-6)
    np.testing.assert_allclose(_np(d.variance), td.variance.numpy(),
                               rtol=1e-5)
    paddle.seed(5)
    s = _np(d.sample([5000]))
    assert s.shape == (5000, 3) and (s.sum(-1) == 10).all()
    np.testing.assert_allclose(s.mean(0), [2.0, 3.0, 5.0], atol=0.1)


def test_gamma_log_prob_entropy_kl():
    from paddle_tpu.distribution import Gamma
    c, r = np.float32(3.0), np.float32(2.0)
    d = Gamma(c, r)
    td = torch.distributions.Gamma(torch.tensor(c), torch.tensor(r))
    x = np.linspace(0.2, 5.0, 7).astype("float32")
    np.testing.assert_allclose(_np(d.log_prob(paddle.to_tensor(x))),
                               td.log_prob(torch.tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(_np(d.entropy())), float(td.entropy()),
                               rtol=1e-5)
    np.testing.assert_allclose(float(_np(d.mean)), 1.5, rtol=1e-6)
    d2 = Gamma(np.float32(1.5), np.float32(1.0))
    td2 = torch.distributions.Gamma(torch.tensor(1.5), torch.tensor(1.0))
    np.testing.assert_allclose(float(_np(kl_divergence(d, d2))),
                               float(torch.distributions.kl_divergence(td,
                                                                       td2)),
                               rtol=1e-4)


def test_laplace_log_prob_entropy_kl_sampling():
    from paddle_tpu.distribution import Laplace
    d = Laplace(np.float32(1.0), np.float32(2.0))
    td = torch.distributions.Laplace(torch.tensor(1.0), torch.tensor(2.0))
    x = np.array([-2., 0., 1., 4.], "float32")
    np.testing.assert_allclose(_np(d.log_prob(paddle.to_tensor(x))),
                               td.log_prob(torch.tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(_np(d.entropy())), float(td.entropy()),
                               rtol=1e-5)
    d2 = Laplace(np.float32(0.0), np.float32(1.0))
    td2 = torch.distributions.Laplace(torch.tensor(0.0), torch.tensor(1.0))
    np.testing.assert_allclose(float(_np(kl_divergence(d, d2))),
                               float(torch.distributions.kl_divergence(td,
                                                                       td2)),
                               rtol=1e-4)
    paddle.seed(13)
    s = _np(d.sample([40000]))
    assert abs(s.mean() - 1.0) < 0.03 and abs(s.var() - 8.0) < 0.25


def test_lognormal_via_transform_matches_torch():
    from paddle_tpu.distribution import LogNormal
    d = LogNormal(np.float32(0.3), np.float32(0.8))
    td = torch.distributions.LogNormal(torch.tensor(0.3), torch.tensor(0.8))
    x = np.array([0.2, 0.9, 2.5], "float32")
    np.testing.assert_allclose(_np(d.log_prob(paddle.to_tensor(x))),
                               td.log_prob(torch.tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(_np(d.mean)), float(td.mean), rtol=1e-5)
    np.testing.assert_allclose(float(_np(d.variance)), float(td.variance),
                               rtol=1e-5)
    np.testing.assert_allclose(float(_np(d.entropy())), float(td.entropy()),
                               rtol=1e-5)
    d2 = LogNormal(np.float32(0.0), np.float32(1.0))
    td2 = torch.distributions.LogNormal(torch.tensor(0.0), torch.tensor(1.0))
    np.testing.assert_allclose(float(_np(kl_divergence(d, d2))),
                               float(torch.distributions.kl_divergence(td,
                                                                       td2)),
                               rtol=1e-4)


def test_transformed_distribution_chain_matches_torch():
    """sigmoid(affine(N(0,1))) — chained bijectors against torch's
    TransformedDistribution with the same chain."""
    from paddle_tpu.distribution import (AffineTransform, Normal,
                                         SigmoidTransform,
                                         TransformedDistribution)
    d = TransformedDistribution(
        Normal(np.float32(0.0), np.float32(1.0)),
        [AffineTransform(np.float32(0.5), np.float32(2.0)),
         SigmoidTransform()])
    td = torch.distributions.TransformedDistribution(
        torch.distributions.Normal(torch.tensor(0.0), torch.tensor(1.0)),
        [torch.distributions.transforms.AffineTransform(0.5, 2.0),
         torch.distributions.transforms.SigmoidTransform()])
    x = np.array([0.1, 0.4, 0.8, 0.95], "float32")
    np.testing.assert_allclose(_np(d.log_prob(paddle.to_tensor(x))),
                               td.log_prob(torch.tensor(x)).numpy(),
                               rtol=1e-4, atol=1e-5)
    paddle.seed(4)
    s = _np(d.sample([10000]))
    assert ((s > 0) & (s < 1)).all()


def test_tanh_and_power_transform_roundtrip():
    from paddle_tpu.distribution import PowerTransform, TanhTransform
    import jax.numpy as jnp
    x = jnp.linspace(-2.0, 2.0, 9)
    t = TanhTransform()
    np.testing.assert_allclose(np.asarray(t.inverse(t.forward(x))),
                               np.asarray(x), rtol=1e-5, atol=1e-6)
    tt = torch.distributions.transforms.TanhTransform()
    np.testing.assert_allclose(
        np.asarray(t.forward_log_det_jacobian(x)),
        tt.log_abs_det_jacobian(torch.tensor(np.asarray(x)),
                                tt(torch.tensor(np.asarray(x)))).numpy(),
        rtol=1e-5, atol=1e-6)
    p = PowerTransform(2.0)
    y = jnp.linspace(0.5, 3.0, 5)
    np.testing.assert_allclose(np.asarray(p.inverse(p.forward(y))),
                               np.asarray(y), rtol=1e-6)


def test_independent_sums_event_dims():
    from paddle_tpu.distribution import Independent, Normal
    loc = np.zeros((3, 4), "float32")
    scale = np.ones((3, 4), "float32")
    d = Independent(Normal(loc, scale), 1)
    td = torch.distributions.Independent(
        torch.distributions.Normal(torch.tensor(loc), torch.tensor(scale)), 1)
    x = np.random.RandomState(0).randn(3, 4).astype("float32")
    np.testing.assert_allclose(_np(d.log_prob(paddle.to_tensor(x))),
                               td.log_prob(torch.tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(_np(d.entropy()), td.entropy().numpy(),
                               rtol=1e-5)
