"""Benchmark entry — prints ONE JSON line.

Parent/child protocol: the parent process (what the driver invokes) never
touches JAX.  It re-execs itself as a child with a bounded timeout and
retries, parses the child's final stdout line, and re-prints it.  If every
attempt fails it prints a structured JSON error object instead of dying with
a raw traceback (round-1 failure mode: rc=1 when the TPU tunnel was down).

Metrics: each config reports throughput (tokens/s or imgs/s), plus
  - ``mfu``: achieved FLOP/s (analytic model FLOPs; XLA cost analysis as
    fallback) over the chip's peak bf16 FLOP/s.
  - ``vs_baseline``: EFFICIENCY parity — our MFU over the 50% MFU a
    Megatron-class reference run achieves on its own hardware.  This is the
    honest apples-to-apples claim (VERDICT r3 weak #1): the reference repo
    publishes no numbers (BASELINE.md), and absolute per-chip FLOP/s just
    restates the chip catalog (an A100 has 312e12 peak, a v5e 197e12 — no
    software can change either).  >= 1.0 means the framework drives its
    chip as efficiently as the reference drives an A100.
  - ``vs_a100_flops``: the absolute per-chip ratio (achieved FLOP/s over
    an A100 at 50% MFU), kept so nobody has to reverse-engineer it.

Configs mirror BASELINE.json: gpt2s (default flagship), resnet50, bert_base,
ernie_moe, mnist_lenet.  ``python bench.py --config X`` for one;
``--config all`` for every config (one JSON line each).
"""

import argparse
import json
import os
import subprocess
import sys
import time

A100_PEAK = 312e12          # bf16 FLOP/s
A100_ASSUMED_MFU = 0.5      # megatron-class reference efficiency proxy

_CHIP_PEAKS = {             # bf16 FLOP/s per chip
    "v6e": 918e12, "v6": 918e12,
    "v5p": 459e12,
    "v5e": 197e12, "v5lite": 197e12, "v5litepod": 197e12,
    "v4": 275e12,
    "v3": 123e12,
}


def _chip_peak():
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "").lower().replace(" ", "")
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    for key, peak in _CHIP_PEAKS.items():
        if key in kind or (gen and key == gen):
            return peak
    return None


def _flops_of(compiled):
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        f = float(ca.get("flops", 0.0))
        return f if f > 0 else None
    except Exception:
        return None


def _transformer_train_flops(B, L, n_layers, H, I, V, moe_topk=1,
                             extra_head_h2=0):
    """Analytic model-FLOPs per train step (fwd + bwd = 3x fwd), the
    Megatron/PaLM MFU convention.  XLA cost analysis counts a lax.scan body
    ONCE rather than num_layers times, so scan models understated MFU
    (round-2 bert 0.107*, ernie 0.075* footnotes); this is the honest
    denominator.  Per token per layer (mul+add = 2 FLOPs):
      QKVO projections 8H^2, attention scores+context 4LH, MLP 4HI.
    Head: 2HV per token (+ optional extra H^2 dense, e.g. BERT MLM head)."""
    per_layer = 8 * H * H + 4 * L * H + 4 * H * I * moe_topk
    per_token = n_layers * per_layer + 2 * H * V + 2 * extra_head_h2 * H * H
    return 3.0 * B * L * per_token


def _run_timed(step, args, iters, monitor=None, examples_per_step=0,
               tokens_per_step=0):
    """AOT-compile ``step`` on ``args`` (arg 0 = donated state), run ``iters``
    steps, sync via host transfer of the loss (block_until_ready on this
    tunneled backend returns before the chain completes — observed 2026-07-29).
    Returns (dt_seconds, final_loss, flops_per_step).

    ``monitor``: optional ``telemetry.TrainMonitor`` observing the run —
    per-iteration dispatch wall as ``train_step`` events, the AOT compile as
    a compile event, the final fetch as the device-blocked ``sync`` (which
    feeds the numerics watchdog), plus an HBM census of the final state."""
    import jax
    import numpy as np

    if not hasattr(step, "lower"):  # plain wrapper around an inner jit
        step = jax.jit(step, donate_argnums=(0,))
    t_c = time.perf_counter()
    lowered = step.lower(*args)
    compiled = lowered.compile()
    if monitor is not None:
        # trace + XLA compile — the compile-event convention (telemetry.py)
        monitor.record_compile(("bench_step",), time.perf_counter() - t_c)
    flops = _flops_of(compiled)

    state, rest = args[0], args[1:]
    t_w = time.perf_counter()
    state, loss = compiled(state, *rest)
    if isinstance(loss, tuple):
        loss = loss[0]
    warm_loss = float(np.asarray(loss))  # warmup sync
    if monitor is not None:
        # the warmup execute+fetch is device-blocked wall — record it so a
        # goodput ledger attached to the monitor attributes it to compute
        # instead of leaving a hole of unattributed time
        monitor.record_sync(time.perf_counter() - t_w, loss=warm_loss)

    it_walls = []
    t0 = time.perf_counter()
    if monitor is None:
        for _ in range(iters):
            state, loss = compiled(state, *rest)
            if isinstance(loss, tuple):
                loss = loss[0]
    else:
        # timed window stays clean: only a perf_counter pair and a list
        # append per iteration — monitor bookkeeping (locks, event dicts)
        # happens after dt is taken
        for _ in range(iters):
            it0 = time.perf_counter()
            state, loss = compiled(state, *rest)
            if isinstance(loss, tuple):
                loss = loss[0]
            it_walls.append(time.perf_counter() - it0)
    t_sync = time.perf_counter()
    final_loss = float(np.asarray(loss))
    dt = time.perf_counter() - t0
    if monitor is not None:
        sync_wall = time.perf_counter() - t_sync
        for w in it_walls:
            monitor.record_step(w, trainer="bench",
                                examples=examples_per_step,
                                tokens=tokens_per_step)
        monitor.record_sync(sync_wall, loss=final_loss)
        if isinstance(state, dict):
            monitor.hbm_census(params=state.get("params"),
                               opt=state.get("opt"))
    assert np.isfinite(final_loss), f"non-finite loss {final_loss}"
    return dt, final_loss, flops


def _result(name, unit, items_per_step, iters, dt, flops_per_step, on_tpu, loss):
    thpt = items_per_step * iters / dt
    out = {"metric": name, "value": round(thpt, 1), "unit": unit}
    if flops_per_step:
        achieved = flops_per_step * iters / dt
        peak = _chip_peak() if on_tpu else None
        raw_mfu = achieved / peak if peak else None
        out["mfu"] = round(raw_mfu, 4) if raw_mfu is not None else None
        # efficiency parity: our MFU vs the reference's assumed 50% on A100
        out["vs_baseline"] = (round(raw_mfu / A100_ASSUMED_MFU, 3)
                              if raw_mfu is not None
                              else None) if on_tpu else 0.0
        out["vs_a100_flops"] = round(
            achieved / (A100_ASSUMED_MFU * A100_PEAK), 3) if on_tpu else 0.0
    else:
        # metric unavailable (cost_analysis failed) — null, not 0.0, so a
        # missing measurement can't read as a total regression
        out["mfu"] = None
        out["vs_baseline"] = None if on_tpu else 0.0
        out["vs_a100_flops"] = None if on_tpu else 0.0
    out["loss"] = round(loss, 4)
    out["backend"] = "tpu" if on_tpu else "cpu"
    return out


def _memory_block(ledger):
    """Per-pool live + peak bytes from a ``telemetry_memory.MemoryLedger``
    — the ``memory`` attachment a bench record carries when its byte
    claims are MEASURED (ISSUE 17).  All-zero pools/tiers are dropped so
    the record stays readable; ``tools/bench_diff.py`` diffs the rest."""
    snap = ledger.memory_snapshot()
    pools = {p: {k: int(v) for k, v in row.items()}
             for p, row in snap["pools"].items() if any(row.values())}
    out = {"pools": pools,
           "totals": {k: int(v) for k, v in snap["totals"].items()}}
    tiers = {t: {k: int(v) for k, v in row.items()}
             for t, row in snap["kv_tiers"].items() if any(row.values())}
    if tiers:
        out["kv_tiers"] = tiers
    return out


def _fleet_hcg(**degrees):
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    cfg = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1}
    cfg.update(degrees)
    strategy.hybrid_configs = cfg
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group()


def _bench_gpt(metric, cfg_tpu, geom_tpu, cfg_cpu, geom_cpu, on_tpu):
    """Shared GPT bench harness: build config + hybrid step, time, report.
    A TrainMonitor observes the timed run (external to the step — the
    compiled program is the same one an unmonitored run uses) and its
    snapshot (step p50/p95, tokens/sec, compile count, peak HBM, watchdog)
    rides the BENCH JSON under ``"telemetry"``."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTModel, make_gpt_train_step
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.telemetry import TrainMonitor
    from paddle_tpu.telemetry_ledger import RunLedger

    paddle.seed(0)
    cfg = GPTConfig(**(cfg_tpu if on_tpu else cfg_cpu))
    B, L, iters = geom_tpu if on_tpu else geom_cpu
    hcg = _fleet_hcg()
    model = GPTModel(cfg)
    step, state = make_gpt_train_step(model, AdamW(3e-4, weight_decay=0.01),
                                      hcg, remat=False)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L)))
    y = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L)))
    args = (state, jax.random.key(0), np.float32(3e-4), x, y)
    mon = TrainMonitor()
    # goodput ledger over the measured window: AOT compile → compile,
    # warmup + final fetch → compute, per-iteration dispatch →
    # host_dispatch; the remainder is unattributed and REPORTED as such
    ledger = RunLedger()
    mon.set_ledger(ledger)
    dt, loss, _ = _run_timed(step, args, iters, monitor=mon,
                             examples_per_step=B, tokens_per_step=B * L)
    flops = _transformer_train_flops(B, L, cfg.num_layers, cfg.hidden_size,
                                     cfg.intermediate_size, cfg.vocab_size)
    out = _result(metric, "tokens/s/chip", B * L, iters, dt, flops, on_tpu,
                  loss)
    tel = mon.summary()
    sw = tel["step_wall_s"] or {}

    def ms(v):
        return None if v is None else round(v * 1e3, 3)

    out["telemetry"] = {
        "steps": tel["steps"],
        "step_ms_p50": ms(sw.get("p50")),
        "step_ms_p95": ms(sw.get("p95")),
        "tokens_per_sec": (None if tel["tokens_per_sec"] is None
                           else round(tel["tokens_per_sec"], 1)),
        "compile_misses": tel["compile"]["misses"],
        "compile_wall_s": round(tel["compile"]["wall_s"], 3),
        "peak_hbm_bytes": tel["hbm"]["peak_bytes"],
        "hbm_params_bytes": tel["hbm"]["params_bytes"],
        "hbm_opt_bytes": tel["hbm"]["opt_bytes"],
        "watchdog_non_finite": tel["watchdog"]["non_finite"],
        "watchdog_loss_spikes": tel["watchdog"]["loss_spikes"],
    }
    snap = ledger.snapshot()
    out["telemetry"]["goodput"] = {
        "goodput": round(snap["goodput"], 4),
        "elapsed_s": round(snap["elapsed_s"], 3),
        "buckets_s": {k: round(v, 4) for k, v in snap["buckets_s"].items()},
        "unattributed_frac": round(snap["fractions"]["unattributed"], 4),
        "overflow_s": round(snap["overflow_s"], 4),
    }
    return out


def bench_gpt2s(on_tpu):
    # B=16 + fully-unrolled layer scan measured best on v5e (see
    # BENCH_NOTES.md sweep: 113.5k tok/s vs 91.9k at the round-1 config)
    return _bench_gpt(
        "gpt2s_train_tokens_per_sec",
        dict(vocab_size=50304, hidden_size=768, num_layers=12,
             num_attention_heads=12, max_position_embeddings=1024,
             compute_dtype="bfloat16", scan_unroll=12), (16, 1024, 30),
        dict(vocab_size=512, hidden_size=128, num_layers=2,
             num_attention_heads=4, max_position_embeddings=128,
             compute_dtype="float32"), (2, 128, 3),
        on_tpu)


def bench_gpt_long(on_tpu):
    """Long-context: L=8192 via the Pallas flash kernel (O(L) memory —
    the dense path would need a 64M-entry score matrix per head)."""
    return _bench_gpt(
        "gpt_long8k_train_tokens_per_sec",
        dict(vocab_size=50304, hidden_size=768, num_layers=12,
             num_attention_heads=12, max_position_embeddings=8192,
             compute_dtype="bfloat16", scan_unroll=12), (1, 8192, 20),
        dict(vocab_size=512, hidden_size=128, num_layers=2,
             num_attention_heads=4, max_position_embeddings=512,
             compute_dtype="float32"), (1, 512, 3),
        on_tpu)


def bench_bert_base(on_tpu):
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.bert import BertConfig, BertModel, make_bert_train_step
    from paddle_tpu.optimizer import AdamW

    paddle.seed(0)
    if on_tpu:
        cfg = BertConfig(vocab_size=30528, hidden_size=768, num_hidden_layers=12,
                         num_attention_heads=12, max_position_embeddings=512,
                         compute_dtype="bfloat16", scan_unroll=12)
        B, L, iters = 16, 512, 20
    else:
        cfg = BertConfig(vocab_size=512, hidden_size=128, num_hidden_layers=2,
                         num_attention_heads=4, max_position_embeddings=128,
                         compute_dtype="float32")
        B, L, iters = 2, 64, 3

    hcg = _fleet_hcg()
    model = BertModel(cfg)
    step, state = make_bert_train_step(model, AdamW(1e-4, weight_decay=0.01),
                                       hcg, remat=False)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L)))
    mlm = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L)))
    nsp = jnp.asarray(rng.randint(0, 2, (B,)))
    args = (state, np.float32(1e-4), ids, mlm, nsp)
    dt, loss, _ = _run_timed(step, args, iters)
    flops = _transformer_train_flops(B, L, cfg.num_hidden_layers,
                                     cfg.hidden_size, cfg.intermediate_size,
                                     cfg.vocab_size, extra_head_h2=1)
    return _result("bert_base_pretrain_tokens_per_sec", "tokens/s/chip",
                   B * L, iters, dt, flops, on_tpu, loss)


def bench_ernie_moe(on_tpu):
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.ernie_moe import (ErnieMoeConfig, ErnieMoeModel,
                                             make_ernie_moe_train_step)
    from paddle_tpu.optimizer import AdamW

    paddle.seed(0)
    if on_tpu:
        cfg = ErnieMoeConfig(vocab_size=30528, hidden_size=768, num_layers=6,
                             num_attention_heads=12, num_experts=8,
                             max_position_embeddings=512,
                             compute_dtype="bfloat16", scan_unroll=6)
        B, L, iters = 8, 512, 20
    else:
        cfg = ErnieMoeConfig(vocab_size=512, hidden_size=128, num_layers=2,
                             num_attention_heads=4, num_experts=4,
                             max_position_embeddings=128,
                             compute_dtype="float32")
        B, L, iters = 2, 64, 3

    hcg = _fleet_hcg()
    model = ErnieMoeModel(cfg)
    step, state = make_ernie_moe_train_step(
        model, AdamW(1e-4, weight_decay=0.01), hcg, remat=False)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L)))
    lbl = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L)))
    args = (state, np.float32(1e-4), ids, lbl)
    dt, loss, _ = _run_timed(step, args, iters)
    flops = _transformer_train_flops(B, L, cfg.num_layers, cfg.hidden_size,
                                     cfg.expert_hidden_size, cfg.vocab_size,
                                     moe_topk=cfg.top_k)
    return _result("ernie_moe_train_tokens_per_sec", "tokens/s/chip",
                   B * L, iters, dt, flops, on_tpu, loss)


def _vision_step(model, lr, B, shape, n_classes, dtype):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu.nn.functional as F
    from paddle_tpu.jit.functional import make_train_step
    from paddle_tpu.optimizer import Momentum

    opt = Momentum(learning_rate=lr, momentum=0.9, weight_decay=1e-4)
    step, state = make_train_step(model, lambda out, y: F.cross_entropy(out, y), opt)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.standard_normal((B,) + shape).astype(np.float32), dtype=dtype)
    y = jnp.asarray(rng.randint(0, n_classes, (B,)))
    return step, (state, jax.random.key(0), np.float32(lr), (x,), (y,))


def bench_resnet50(on_tpu):
    import paddle_tpu as paddle
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    if on_tpu:  # NHWC: TPU-preferred conv layout (VERDICT r2 #3)
        model, B, shape, iters = \
            resnet50(data_format="NHWC"), 128, (224, 224, 3), 20
        dtype = "bfloat16"
    else:  # same model, shrunk input — the metric name stays truthful
        model, B, shape, iters = resnet50(num_classes=10), 2, (3, 64, 64), 2
        dtype = "float32"
    step, args = _vision_step(model, 0.1, B, shape, 1000 if on_tpu else 10, dtype)
    dt, loss, flops = _run_timed(step, args, iters)
    return _result("resnet50_train_imgs_per_sec", "imgs/s/chip",
                   args[3][0].shape[0], iters, dt, flops, on_tpu, loss)


def bench_mnist_lenet(on_tpu):
    import paddle_tpu as paddle
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    B, iters = (512, 30) if on_tpu else (32, 3)
    model = LeNet()
    step, args = _vision_step(model, 0.01, B, (1, 28, 28), 10, "float32")
    dt, loss, flops = _run_timed(step, args, iters)
    return _result("mnist_lenet_train_imgs_per_sec", "imgs/s/chip",
                   B, iters, dt, flops, on_tpu, loss)


def bench_gpt_decode(on_tpu):
    """Serving decode throughput: greedy KV-cache generation on gpt2-small
    (prefill amortized into the measured program — the user-visible serving
    number).  No training-FLOPs MFU (decode is bandwidth-bound by design);
    vs_baseline is null — the reference publishes no decode figure."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTModel

    paddle.seed(0)
    # PADDLE_TPU_DECODE_KV=int8 A/Bs the quantized cache (half the decode
    # HBM traffic — the headline lever for this bandwidth-bound config)
    kv = os.environ.get("PADDLE_TPU_DECODE_KV") or None
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_attention_heads=12, max_position_embeddings=1024,
                        compute_dtype="bfloat16", kv_cache_dtype=kv)
        B, P, N, iters = 8, 128, 128, 5
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=128,
                        compute_dtype="float32", kv_cache_dtype=kv)
        B, P, N, iters = 2, 8, 8, 2
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    run = model._gen_program(P, N, 1.0, None, None, True)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, cfg.vocab_size,
                                                       (B, P)))
    # warm compile
    out = run(params, ids, jax.random.key(0))
    np.asarray(out[0, 0])
    # _run_timed discipline: queue all iterations, then ONE host fetch that
    # depends on every output (iterations are independent, so the final
    # fetch must touch all of them — a single out[0,0] would only prove the
    # last one ran)
    t0 = time.perf_counter()
    outs = [run(params, ids, jax.random.key(i)) for i in range(iters)]
    np.asarray(jnp.stack([o[0, 0] for o in outs]))
    dt = time.perf_counter() - t0
    thpt = B * N * iters / dt
    return {"metric": "gpt2s_decode_tokens_per_sec", "value": round(thpt, 1),
            "unit": "tokens/s/chip", "mfu": None, "vs_baseline": None,
            "vs_a100_flops": None,
            "loss": 0.0, "backend": "tpu" if on_tpu else "cpu"}


def bench_gpt_serving(on_tpu):
    """ENGINE-level serving throughput on a mixed arrival workload — the
    user-visible serving number (gpt_decode times solo greedy decode only).
    Drives the ragged paged engine: requests arrive WHILE others decode,
    and every scheduler tick is ONE compiled mixed prefill+decode program
    (serving_paged.RaggedPagedContinuousBatchingEngine), so the figure
    includes admission, scheduling, paging, and preemption overheads.
    MFU/roofline attribution comes from the compile-seam cost analysis
    (telemetry attribute_cost): per-dispatch model FLOPs over tick wall
    — ``mfu`` needs a configured peak (PADDLE_TPU_PEAK_FLOPS), the raw
    model-FLOPs/s and arithmetic intensity report regardless.
    vs_baseline is null — the reference publishes no serving figure.
    PADDLE_TPU_DECODE_KV=int8 A/Bs the quantized pool."""
    import jax  # noqa: F401 — backend must be up before engine build
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTModel
    from paddle_tpu.serving import RaggedPagedContinuousBatchingEngine

    paddle.seed(0)
    kv = os.environ.get("PADDLE_TPU_DECODE_KV") or None
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_attention_heads=12,
                        max_position_embeddings=1024,
                        compute_dtype="bfloat16", kv_cache_dtype=kv)
        slots, max_len, bs, budget = 8, 512, 16, 256
        buckets, n_reqs, lo_new, hi_new = [64, 128], 24, 48, 96
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=128,
                        compute_dtype="float32", kv_cache_dtype=kv)
        slots, max_len, bs, budget = 2, 64, 8, 24
        buckets, n_reqs, lo_new, hi_new = [8, 16], 6, 4, 8
    from paddle_tpu.telemetry import Tracer

    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    rng = np.random.RandomState(0)
    reqs = [([int(t) for t in rng.randint(1, cfg.vocab_size,
                                          rng.randint(buckets[0] // 2,
                                                      buckets[-1] + 1))],
             int(rng.randint(lo_new, hi_new + 1))) for _ in range(n_reqs)]

    def run_once(tracer=None, spec=False):
        # the speculative arm SELF-drafts (draft == target): the upper
        # bound on acceptance (~1.0 — draft and verify argmax the same
        # weights), so the A/B isolates the scheduling win (one host
        # sync per K+1 tokens) from draft quality
        kw = (dict(draft_model=model, draft_params=params, draft_k=4)
              if spec else {})
        eng = RaggedPagedContinuousBatchingEngine(
            model, params, max_slots=slots, max_len=max_len, block_size=bs,
            prompt_buckets=buckets, token_budget=budget, tracer=tracer,
            **kw)
        added = 0
        while added < len(reqs) or eng.pending():
            # staggered arrivals: two new requests per tick, so admission
            # prefill chunks and running decodes share the same programs
            for _ in range(2):
                if added < len(reqs):
                    eng.add_request(*reqs[added])
                    added += 1
            eng.step()
        out = eng.pop_finished()
        return sum(len(v) for v in out.values()), eng

    # warm WITH a costed throwaway tracer: compiles the (budget, C)
    # family AND probes each program's XLA cost analysis once (digest-
    # cached process-wide).  The measured tracer is pre-seeded from it so
    # the timed window pays zero probe work — no relower/compile wall
    # leaks into tokens/s, tick/TTFT percentiles, or the MFU denominator
    warm_tracer = Tracer(capacity=16384, attribute_cost=True)
    run_once(warm_tracer)

    def timed(warm, spec):
        # a FRESH measured tracer per attempt, pre-seeded with the warm
        # run's program costs, so no probe work or stale events leak in
        tr = Tracer(capacity=16384, attribute_cost=True)
        for _lbl, _cost in warm.program_costs().items():
            tr.record_cost(_lbl, _cost)
        t0 = time.perf_counter()
        n, e = run_once(tr, spec=spec)
        wall = time.perf_counter() - t0
        assert n == sum(x for _, x in reqs), (n, spec, "tokens dropped")
        return n, e, wall, tr

    total, eng, dt, tracer = timed(warm_tracer, False)

    # ---- speculative A/B: the SAME seeded mixed-arrival load through
    # the ragged engine's fused draft+verify tick (ISSUE 13) ----
    spec_warm = Tracer(capacity=16384, attribute_cost=True)
    run_once(spec_warm, spec=True)
    stotal, seng, sdt, spec_tracer = timed(spec_warm, True)
    # the acceptance pin: at self-draft acceptance (>= 0.5 by huge
    # margin — argmax of identical weights) the spec-ragged tick must
    # STRICTLY beat plain ragged decode, or the config fails instead of
    # shading a number.  One bounded re-measure of BOTH arms absorbs
    # scheduler jitter on small-margin hosts — the re-measured numbers
    # are the ones recorded, so the record stays honest either way.
    if float(seng.metrics()["acceptance_rate"]) >= 0.5 \
            and stotal / sdt <= total / dt:
        total, eng, dt, tracer = timed(warm_tracer, False)
        stotal, seng, sdt, spec_tracer = timed(spec_warm, True)
    sm = seng.metrics()
    spec_tok_s = stotal / sdt
    acceptance = float(sm["acceptance_rate"])
    stel = spec_tracer.summary()
    if acceptance >= 0.5:
        assert spec_tok_s > total / dt, (spec_tok_s, total / dt,
                                         acceptance)
    # telemetry snapshot for the (possibly re-measured) plain run the
    # headline number reports
    tel = tracer.summary()
    tick = tel["tick_wall_s"] or {}
    req = tel["requests"]
    mfu = tel["mfu"]

    def ms(v):
        return None if v is None else round(v * 1e3, 3)

    out = {"metric": "gpt_serving_tokens_per_sec",
            "value": round(total / dt, 1), "unit": "tokens/s/chip",
            # null unless PADDLE_TPU_PEAK_FLOPS declares the roofline;
            # the raw model-FLOPs attribution reports either way
            "mfu": mfu["mfu"],
            "vs_baseline": None, "vs_a100_flops": None,
            "loss": 0.0, "backend": "tpu" if on_tpu else "cpu",
            "requests": len(reqs),
            "mixed_steps": int(eng.mixed_steps),
            "ragged_steps": int(eng.ragged_steps),
            # telemetry snapshot for the measured run: the warm run built
            # every program, so compile misses here == recompile storms
            "telemetry": {
                "ticks": tel["ticks"],
                "tick_ms_p50": ms(tick.get("p50")),
                "tick_ms_p95": ms(tick.get("p95")),
                "tick_ms_max": ms(tick.get("max")),
                "compile_hits": tel["compile"]["hits"],
                "compile_misses": tel["compile"]["misses"],
                "compile_wall_s": round(tel["compile"]["wall_s"], 3),
                "ttft_ms_p50": ms((req["ttft_s"] or {}).get("p50")),
                "ttft_ms_p99": ms((req["ttft_s"] or {}).get("p99")),
                "itl_ms_p50": ms((req["inter_token_s"] or {}).get("p50")),
                "itl_ms_p99": ms((req["inter_token_s"] or {}).get("p99")),
                "preempted": req["replays"],
                # MFU/roofline attribution (cost_analysis at the compile
                # seams): non-null on CPU too — flops come from XLA, not
                # from a device-specific counter
                "model_flops_total": mfu["model_flops_total"],
                "model_flops_per_s": mfu["model_flops_per_s"],
                "arithmetic_intensity": mfu["arithmetic_intensity"],
                "mfu": mfu["mfu"],
                # spec-ragged A/B fields (tools/bench_diff.py judges
                # these direction-aware between rounds)
                "acceptance_rate": round(acceptance, 4),
                "accepted_tokens_per_s": round(
                    float(sm["tokens_accepted"]) / sdt, 1),
                "spec_tokens_per_sec": round(spec_tok_s, 1),
            }}
    out["speculative"] = {
        "draft": "self", "draft_k": int(seng.K),
        "tokens_per_sec": round(spec_tok_s, 1),
        "speedup_vs_plain": round(spec_tok_s / (total / dt), 3),
        "acceptance_rate": round(acceptance, 4),
        "spec_rounds": int(seng.spec_rounds),
        "tokens_drafted": int(sm["tokens_drafted"]),
        "tokens_accepted": int(sm["tokens_accepted"]),
        # MFU attribution over the spec run (accepted-token roofline)
        "mfu": stel["mfu"]["mfu"],
        "model_flops_per_s": stel["mfu"]["model_flops_per_s"],
    }
    return out


def bench_gpt_serving_warmup(on_tpu):
    """Cold-start vs warmed-start A/B on the ragged serving engine — the
    compile-latency number (ISSUE 7): time from a fresh engine's first
    add_request to its first token, and the count of XLA compiles paid ON
    the serving path, with and without the AOT warmup pass
    (engine.warmup() precompiles the whole (token_budget, table-width)
    program grid before traffic).  The warmed engine must pay ZERO
    in-serve compiles and a strictly lower first-token latency — both
    asserted, so a regression fails the config rather than shading a
    number."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTModel
    from paddle_tpu.serving import RaggedPagedContinuousBatchingEngine
    from paddle_tpu.telemetry import Tracer

    kv = os.environ.get("PADDLE_TPU_DECODE_KV") or None
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_attention_heads=12,
                        max_position_embeddings=1024,
                        compute_dtype="bfloat16", kv_cache_dtype=kv)
        slots, max_len, bs, budget = 8, 512, 16, 256
        buckets, plen, n_new = [64, 128], 96, 32
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=128,
                        compute_dtype="float32", kv_cache_dtype=kv)
        slots, max_len, bs, budget = 2, 64, 8, 24
        buckets, plen, n_new = [8, 16], 12, 4
    rng = np.random.RandomState(0)
    prompt = [int(t) for t in rng.randint(1, cfg.vocab_size, plen)]

    def run_phase(warm):
        # a fresh model per phase = a fresh program cache: the cold phase
        # really pays its compiles, the warm phase really pre-pays them
        paddle.seed(0)
        model = GPTModel(cfg)
        params = {n: p._data for n, p in model.named_parameters()}
        tracer = Tracer(capacity=8192)
        eng = RaggedPagedContinuousBatchingEngine(
            model, params, max_slots=slots, max_len=max_len, block_size=bs,
            prompt_buckets=buckets, token_budget=budget, tracer=tracer)
        report = eng.warmup(max_workers=1) if warm else None
        warm_misses = eng._compile_misses
        seen = []
        eng.add_request(list(prompt), n_new,
                        on_token=lambda r, t, d: seen.append(t))
        t0 = time.perf_counter()
        while not seen:
            eng.step()
        first_s = time.perf_counter() - t0
        eng.run_to_completion(max_ticks=1000)
        return {
            "first_token_ms": round(first_s * 1e3, 3),
            "serve_compile_misses": eng._compile_misses - warm_misses,
            "warmup_programs": 0 if report is None else report["programs"],
            "warmup_wall_s": (None if report is None
                              else round(report["wall_s"], 3)),
            "compile": tracer.summary()["compile"],
        }

    cold = run_phase(False)
    warmed = run_phase(True)
    assert warmed["serve_compile_misses"] == 0, warmed
    assert warmed["serve_compile_misses"] < cold["serve_compile_misses"], \
        (cold, warmed)
    assert warmed["first_token_ms"] < cold["first_token_ms"], (cold, warmed)
    return {"metric": "gpt_serving_warmup_first_token_ms",
            "value": warmed["first_token_ms"], "unit": "ms",
            "mfu": None, "vs_baseline": None, "vs_a100_flops": None,
            "loss": 0.0, "backend": "tpu" if on_tpu else "cpu",
            "cold": cold, "warm": warmed,
            "first_token_speedup": round(
                cold["first_token_ms"] / warmed["first_token_ms"], 3)}


def bench_gpt_kv_tier(on_tpu):
    """Tiered-KV A/B for a long shared system prompt (ISSUE 14): (a)
    COLD recompute — no prefix reuse, the prompt pays its full ragged
    prefill every time; (b) WARM lower-tier restore — the prompt's KV
    pages sit in the TieredKVStore's host-DRAM tier (flushed out of HBM
    between repeats), admission restores them device-side and computes
    only the bucket's last block; (c) CROSS-REPLICA migration — a
    prefill-role replica produces the pages, the gateway migrates them
    under a byte budget into a decode-role replica's store, and the
    request decodes there token-for-token equal to the solo oracle.
    The acceptance pin: warm-tier p50 TTFT strictly beats cold
    recompute (one bounded re-measure absorbs scheduler jitter; the
    re-measured numbers are the ones recorded).  All engines are AOT
    warmed, so zero in-serve compiles pollute any arm — asserted."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTModel
    from paddle_tpu.serving import RaggedPagedContinuousBatchingEngine
    from paddle_tpu.gateway import ServingGateway
    from paddle_tpu.kv_store import TieredKVStore

    kv = os.environ.get("PADDLE_TPU_DECODE_KV") or None
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_attention_heads=12,
                        max_position_embeddings=1024,
                        compute_dtype="bfloat16", kv_cache_dtype=kv)
        slots, max_len, bs, budget = 4, 512, 16, 64
        buckets, plen, n_new, reps = [64, 256], 240, 16, 5
    else:
        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=128,
                        compute_dtype="float32", kv_cache_dtype=kv)
        slots, max_len, bs, budget = 2, 96, 8, 16
        buckets, plen, n_new, reps = [16, 64], 60, 6, 5
    paddle.seed(0)
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    rng = np.random.RandomState(0)
    # the shared system prompt: spans many blocks, so the warm arm's
    # suffix (one block) is a fraction of the cold arm's prefill ticks
    prompt = [int(t) for t in rng.randint(1, cfg.vocab_size, plen)]
    oracle = [int(t) for t in np.asarray(model.generate(
        params, jnp.asarray([prompt], jnp.int32), n_new, greedy=True))[0]]

    def mk(store=None, prefix=None):
        return RaggedPagedContinuousBatchingEngine(
            model, params, max_slots=slots, max_len=max_len,
            block_size=bs, prompt_buckets=buckets, token_budget=budget,
            enable_prefix_cache=(store is not None if prefix is None
                                 else prefix), kv_store=store)

    def ttft_once(eng):
        first = []
        eng.add_request(list(prompt), n_new,
                        on_token=lambda r, t, d:
                        first.append(time.perf_counter())
                        if t is not None and not first else None)
        t0 = time.perf_counter()
        while eng.pending():
            eng.step()
        out = eng.pop_finished()
        toks = next(iter(out.values()))
        assert toks == oracle, "tiered serving diverged from the oracle"
        return (first[0] - t0) * 1e3

    def measure_cold_warm():
        cold_eng = mk(prefix=False)       # no reuse: every repeat recomputes
        cold_eng.warmup(max_workers=1)
        cold = sorted(ttft_once(cold_eng) for _ in range(reps))
        store = TieredKVStore()
        warm_eng = mk(store=store)
        warm_eng.warmup(max_workers=1)
        misses0 = warm_eng._compile_misses
        ttft_once(warm_eng)               # prime: publishes the pages
        warm = []
        for _ in range(reps):
            # HBM emptied every repeat: the hit is a LOWER-TIER restore,
            # never a resident-HBM shortcut
            warm_eng.flush_prefix()
            warm.append(ttft_once(warm_eng))
        warm.sort()
        assert warm_eng._compile_misses == misses0, "in-serve compiles"
        return cold, warm, store, warm_eng

    def p(vals, q):
        return vals[min(len(vals) - 1, int(round(q * (len(vals) - 1))))]

    from paddle_tpu.telemetry_memory import MemoryLedger
    mem = MemoryLedger()
    with mem:   # active ledger: every TieredKVStore mutation resyncs its
        # dram/disk tier bytes; a census pins the device-resident side
        cold, warm, store, warm_eng = measure_cold_warm()
        if p(warm, 0.5) >= p(cold, 0.5):
            # one bounded re-measure absorbs jitter on small-margin hosts;
            # the re-measured numbers are the ones recorded either way
            cold, warm, store, warm_eng = measure_cold_warm()
        assert p(warm, 0.5) < p(cold, 0.5), (warm, cold)

        # device-side KV bytes (the hbm tier row): register the warm
        # engine's params + paged caches, then one census
        warm_eng.attach_memory(mem)
        warm_eng.refresh_memory()
        mem.census()

        # ---- cross-replica migration arm: fresh engines per repeat so
        # every pass really migrates (a shared decode replica would
        # HBM-hit) ----
        mig_ttfts, migrated_bytes = [], 0
        for _ in range(3):
            gw = ServingGateway(migration_bytes_per_tick=None)
            prefill_eng, decode_eng = mk(prefix=True), \
                mk(store=TieredKVStore())
            prefill_eng.warmup(max_workers=1)
            decode_eng.warmup(max_workers=1)
            m0 = prefill_eng._compile_misses + decode_eng._compile_misses
            gw.add_replica(prefill_eng, "pf", role="prefill")
            gw.add_replica(decode_eng, "dc", role="decode")
            h = gw.submit(list(prompt), n_new)
            while gw.pending():
                gw.step()
            out = gw.pop_finished()
            assert h.status == "finished" and out[h.gid] == oracle, h
            assert h.replica == "dc", h.replica
            snap = gw.kvstore_snapshot()
            assert snap["counters"]["migrations_completed"] == 1, snap
            migrated_bytes = int(snap["counters"]["migrated_bytes"])
            assert prefill_eng._compile_misses + decode_eng._compile_misses \
                == m0, "in-serve compiles in the migration arm"
            mig_ttfts.append((h.first_token_at - h.submitted_at) * 1e3)
        mig_ttfts.sort()

    hit_rate = store.hit_rate()
    mem_snap = mem.memory_snapshot()
    tier_bytes = {t: int(r["bytes"])
                  for t, r in mem_snap["kv_tiers"].items()}
    tier_peak_bytes = {t: int(r["peak_bytes"])
                       for t, r in mem_snap["kv_tiers"].items()}
    return {"metric": "gpt_kv_tier_restore_ttft_ms",
            "value": round(p(warm, 0.5), 3), "unit": "ms",
            "mfu": None, "vs_baseline": None, "vs_a100_flops": None,
            "loss": 0.0, "backend": "tpu" if on_tpu else "cpu",
            "prompt_tokens": plen, "blocks": plen // bs,
            "kv_tier": {
                "cold_ttft_ms_p50": round(p(cold, 0.5), 3),
                "warm_ttft_ms_p50": round(p(warm, 0.5), 3),
                "restore_ttft_p99": round(p(warm, 0.99), 3),
                "warm_speedup": round(p(cold, 0.5) / p(warm, 0.5), 3),
                "tier_hit_rate": (None if hit_rate is None
                                  else round(hit_rate, 4)),
                "restored_blocks": int(warm_eng.metrics()
                                       ["kvstore_restored_blocks"]),
                "migrated_bytes": migrated_bytes,
                "migration_ttft_ms_p50": round(p(mig_ttfts, 0.5), 3),
                # measured per-tier KV bytes from the memory ledger
                # (ISSUE 17): hbm from the census over the warm engine's
                # paged caches, dram/disk from the store tier counters
                "tier_bytes": tier_bytes,
                "tier_peak_bytes": tier_peak_bytes,
            },
            "memory": _memory_block(mem)}


def bench_gpt_gateway(on_tpu):
    """Overload A/B through the serving gateway (ISSUE 9): the SAME
    offered load — more requests than the replica fleet can hold — is
    pushed through (a) a bounded gateway queue that sheds past its depth
    limit with structured ``Overloaded`` rejections, and (b) an
    effectively unbounded queue that admits everything.  Shedding is the
    tail-latency contract: admitted requests under (a) must see a
    strictly lower p99 TTFT than under (b), because nobody waits behind
    work the fleet cannot start — asserted, so a routing/admission
    regression fails the config rather than shading a number.  Also
    asserted: no silent drops (every offered request terminates as
    finished or structured-shed) and a clean fleet at quiescence."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.gateway import ServingGateway
    from paddle_tpu.models.gpt import GPTConfig, GPTModel
    from paddle_tpu.serving import RaggedPagedContinuousBatchingEngine
    from paddle_tpu.telemetry import Tracer

    kv = os.environ.get("PADDLE_TPU_DECODE_KV") or None
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_attention_heads=12,
                        max_position_embeddings=1024,
                        compute_dtype="bfloat16", kv_cache_dtype=kv)
        slots, max_len, bs, budget = 4, 256, 16, 128
        buckets, n_reqs, lo_new, hi_new, depth = [64], 48, 24, 48, 4
        replicas = 2
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=128,
                        compute_dtype="float32", kv_cache_dtype=kv)
        slots, max_len, bs, budget = 2, 64, 8, 24
        buckets, n_reqs, lo_new, hi_new, depth = [8, 16], 24, 6, 12, 3
        replicas = 2
    paddle.seed(0)
    model = GPTModel(cfg)
    params = {n: p._data for n, p in model.named_parameters()}
    rng = np.random.RandomState(0)
    reqs = [([int(t) for t in rng.randint(1, cfg.vocab_size,
                                          rng.randint(buckets[0] // 2,
                                                      buckets[-1] + 1))],
             int(rng.randint(lo_new, hi_new + 1))) for _ in range(n_reqs)]

    def run_phase(max_queue_depth, fleet=False):
        eng = lambda: RaggedPagedContinuousBatchingEngine(  # noqa: E731
            model, params, max_slots=slots, max_len=max_len,
            block_size=bs, prompt_buckets=buckets, token_budget=budget,
            tracer=Tracer())
        gw = ServingGateway(max_queue_depth=max_queue_depth,
                            tracer=Tracer(capacity=16384))
        for i in range(replicas):
            gw.add_replica(eng(), f"r{i}")
        collector = None
        if fleet:
            # federate the phase through a FleetCollector scraping an
            # UNSTARTED ops server (render()-only, no port): the record
            # gains the fleet rollup bench_diff judges (merged TTFT p99,
            # tokens/s, occupancy) — pure pull telemetry, zero effect on
            # scheduling or lowerings
            from paddle_tpu.ops_server import OpsServer
            from paddle_tpu.telemetry_fleet import FleetCollector
            from paddle_tpu.telemetry_slo import SLOMonitor
            gw.set_slo(SLOMonitor(resolution_s=0.5))
            srv = OpsServer()
            srv.attach(gw, "gateway")
            srv.attach(gw._slo, "slo")
            collector = FleetCollector(interval_s=0.5)
            collector.add_target("gateway", server=srv)
            collector.scrape_once()     # baseline for counter deltas
        # the OVERLOAD shape: arrivals outpace the fleet's drain rate
        # (two per scheduler round, gpt_serving's stagger) — everything
        # past capacity either queues (unbounded) or sheds (bounded)
        t0 = time.perf_counter()
        handles = []
        for p, n in reqs:
            handles.append(gw.submit(p, n))
            if len(handles) % 2 == 0:
                gw.step()
        gw.run_to_completion(max_ticks=100000)
        wall = time.perf_counter() - t0
        admitted = [r for r in handles if r.status == "finished"]
        shed = [r for r in handles if r.status == "shed"]
        assert len(admitted) + len(shed) == len(handles), \
            [r.status for r in handles]          # no silent drops
        assert all(r.error is not None for r in shed)   # structured
        ttfts = np.asarray([r.first_token_at - r.submitted_at
                            for r in admitted])
        for name in ("r0", "r1"):
            assert gw.replica(name).engine.blocks_in_use == 0
        out = {
            "admitted": len(admitted), "shed": len(shed),
            "wall_s": round(wall, 3),
            "ttft_ms_p50": round(float(np.percentile(ttfts, 50)) * 1e3, 3),
            "ttft_ms_p99": round(float(np.percentile(ttfts, 99)) * 1e3, 3),
            "tokens": int(sum(len(r.tokens) for r in admitted)),
        }
        if collector is not None:
            out["fleet"] = collector.scrape_once()["rollup"]
        return out

    run_phase(10 ** 9)                 # warm: compiles the program family
    unbounded = run_phase(10 ** 9)
    bounded = run_phase(depth, fleet=True)
    fleet_block = bounded.pop("fleet", None)
    assert bounded["shed"] > 0, bounded
    assert unbounded["shed"] == 0, unbounded
    assert bounded["ttft_ms_p99"] < unbounded["ttft_ms_p99"], \
        (bounded, unbounded)
    rec = {"metric": "gpt_gateway_ttft_ms_p99",
           "value": bounded["ttft_ms_p99"], "unit": "ms",
           "mfu": None, "vs_baseline": None, "vs_a100_flops": None,
           "loss": 0.0, "backend": "tpu" if on_tpu else "cpu",
           "offered": len(reqs), "replicas": replicas,
           "queue_depth": depth,
           "bounded": bounded, "unbounded": unbounded,
           "p99_ttft_improvement": round(
               unbounded["ttft_ms_p99"] / bounded["ttft_ms_p99"], 3)}
    if fleet_block is not None:
        rec["fleet"] = fleet_block     # bench_diff's _FLEET_FIELDS rows
    return rec


def bench_gpt_autoscale(on_tpu):
    """Flash-crowd A/B on the fake-clock simulation harness: the SAME
    offered load (identical seed, arrival process and request shapes)
    against a FIXED single-replica fleet vs an ``ElasticAutoscaler``-
    managed fleet (paddle_tpu/autoscaler.py), asserting the autoscaled
    fleet's p99 TTFT and shed rate strictly beat the fixed fleet's, with
    zero dropped requests on both sides and the full decision timeline
    attached to the BENCH JSON.  Latencies are SIMULATED seconds on the
    injected clock — deterministic and backend-independent by
    construction (the record still carries the backend label for
    trajectory honesty); what this benchmarks is the scaling POLICY, not
    the hardware."""
    from paddle_tpu.autoscaler import ElasticAutoscaler
    from paddle_tpu.gateway import ServingGateway
    from paddle_tpu.simulation import (SimClock, SimEngine, SimTracer,
                                       TrafficSim, flash_crowd)
    from paddle_tpu.telemetry_slo import Objective, SLOMonitor

    BASE, SPIKE, AT, DUR = 1.0, 8.0, 20.0, 40.0
    HORIZON, DT, SEED = 180.0, 0.25, 0

    def run(autoscaled):
        clock = SimClock()
        tracer = SimTracer(clock, capacity=16384)
        gw = ServingGateway(clock=clock, max_queue_depth=64,
                            tracer=tracer, stall_threshold_s=30.0)

        def factory():
            return SimEngine(max_slots=4, tracer=SimTracer(clock))

        gw.add_replica(factory(), "r0")
        asc = None
        if autoscaled:
            slo = SLOMonitor([
                Objective.latency("ttft_p99", "ttft_s", 2.0,
                                  compliance=0.9, windows=(30.0, 10.0),
                                  burn_threshold=1.0, for_s=2.0,
                                  clear_s=10.0),
                Objective.ratio("shed_rate", "shed", "submitted", 0.05,
                                windows=(30.0, 10.0), burn_threshold=1.0,
                                for_s=2.0, clear_s=10.0),
            ], clock=clock, resolution_s=1.0, tracer=tracer)
            gw.set_slo(slo)
            asc = ElasticAutoscaler(
                gw, factory, slo=slo, min_replicas=1, max_replicas=4,
                scale_up_cooldown_s=5.0, scale_down_cooldown_s=20.0,
                idle_utilization=0.2, idle_dwell_s=30.0,
                tracer=tracer, clock=clock)
        sim = TrafficSim(gw, clock, flash_crowd(BASE, SPIKE, AT, DUR),
                         dt=DT, seed=SEED, autoscaler=asc)
        rep = sim.run(HORIZON)
        assert not rep["dropped"], rep["dropped"]      # zero drops, always
        return rep

    fixed = run(False)
    auto = run(True)
    assert fixed["offered"] == auto["offered"], (fixed["offered"],
                                                 auto["offered"])
    f_p99, a_p99 = fixed["ttft_s"]["p99"], auto["ttft_s"]["p99"]
    # the A/B contract: at the same offered load the autoscaled fleet
    # strictly beats the fixed fleet on BOTH tail latency and shedding
    assert fixed["shed_rate"] > 0.0, fixed          # the load IS overload
    assert a_p99 < f_p99, (a_p99, f_p99)
    assert auto["shed_rate"] < fixed["shed_rate"], (auto["shed_rate"],
                                                    fixed["shed_rate"])

    def phase(rep):
        return {"offered": rep["offered"], "outcomes": rep["outcomes"],
                "shed_rate": round(rep["shed_rate"], 4),
                "ttft_s_p50": rep["ttft_s"]["p50"],
                "ttft_s_p99": rep["ttft_s"]["p99"]}

    return {"metric": "gpt_autoscale_ttft_s_p99", "value": a_p99,
            "unit": "s", "direction": "lower",
            "mfu": None, "vs_baseline": None, "vs_a100_flops": None,
            "loss": 0.0, "backend": "tpu" if on_tpu else "cpu",
            "sim": {"workload": f"flash_crowd base={BASE}/s "
                                f"spike={SPIKE}/s t=[{AT},{AT + DUR})s",
                    "horizon_s": HORIZON, "dt_s": DT, "seed": SEED,
                    "clock": "simulated"},
            "fixed": phase(fixed), "autoscaled": phase(auto),
            "p99_ttft_improvement": round(f_p99 / a_p99, 3),
            "fleet_peak": max(s["active"] for s in auto["timeline"]),
            "decisions": auto["decisions"]}


def bench_gpt_chaos(on_tpu):
    """Seeded fault-plan A/B on the fake-clock simulation harness (ISSUE
    12): the SAME offered load AND the SAME injected faults — a replica
    crash mid-burst, a stall window, a 40× slow straggler (a 10× one is
    indistinguishable from quarantine-recovery noise at this tick size —
    the straggler must dominate the off-side tail for the A/B to isolate
    hedging), a transient
    dispatch-error window (paddle_tpu/faults.py) — against a gateway
    with resilience OFF vs ON (circuit breakers + bounded retry/backoff
    + TTFT hedging + brownout, paddle_tpu/gateway.py
    ``ResiliencePolicy``).  Asserted chaos acceptance pin: on BOTH sides
    every admitted request reaches a terminal outcome (zero silent
    drops) and every finished stream is an exact oracle prefix (no
    duplicated/garbled tokens); on the resilient side retries stay
    within budget and p99 TTFT is STRICTLY better than resilience-off
    under the identical plan.  Latencies are SIMULATED seconds on the
    injected clock — what this benchmarks is the failure-response
    policy, not the hardware (the record still carries the backend
    label for trajectory honesty)."""
    from paddle_tpu.faults import Fault, FaultPlan, FaultyEngine
    from paddle_tpu.gateway import ServingGateway, ResiliencePolicy
    from paddle_tpu.simulation import (SimClock, SimEngine, SimTracer,
                                       TrafficSim, sim_tokens, steady)

    RATE, HORIZON, DT, SEED = 2.0, 120.0, 0.25, 0
    TTFT_DEADLINE, STALL_THRESHOLD = 60.0, 4.0
    plan = FaultPlan([
        Fault("slow", at_s=20.0, duration_s=40.0, factor=40,
              replica="r0"),
        Fault("crash", at_s=30.0, replica="r1"),
        Fault("dispatch_error", at_s=45.0, duration_s=6.0, replica="r2"),
        Fault("stall", at_s=70.0, duration_s=12.0, replica="r2"),
    ], seed=7)

    def run(resilient):
        clock = SimClock()
        tracer = SimTracer(clock, capacity=32768)
        pol = None
        if resilient:
            pol = ResiliencePolicy(
                retry_budget=3, retry_backoff_s=0.25,
                retry_backoff_max_s=2.0, retry_jitter=0.5, seed=SEED,
                breaker_failures=3, breaker_open_s=2.5,
                hedge=True, hedge_ttft_frac=0.05, max_hedges=8,
                brownout=True, brownout_high=3.0, brownout_low=1.0,
                brownout_down_dwell_s=5.0, brownout_clamp=6,
                brownout_use_slo=False)
        gw = ServingGateway(clock=clock, tracer=tracer,
                            stall_threshold_s=STALL_THRESHOLD,
                            max_queue_depth=256, resilience=pol)
        wrappers = []
        for i in range(3):
            name = f"r{i}"
            eng = SimEngine(max_slots=8, tracer=SimTracer(clock))
            w = FaultyEngine(eng, plan, clock, replica=name)
            wrappers.append(w)
            gw.add_replica(w, name)
        sim = TrafficSim(gw, clock, steady(RATE), dt=DT, seed=SEED,
                         ttft_deadline_s=TTFT_DEADLINE)
        rep = sim.run(HORIZON)
        # chaos acceptance pin, part 1: every admitted request reaches a
        # terminal outcome, and no finished stream is duplicated/garbled
        assert not rep["dropped"], rep["dropped"]
        for h in sim.handles:
            if h.status == "finished":
                assert h.tokens == sim_tokens(h.prompt, len(h.tokens)), \
                    (h.gid, h.tokens)
        if resilient:
            budget = pol.retry_budget
            assert all(h.retries <= budget for h in sim.handles), \
                max(h.retries for h in sim.handles)
        rep["injected"] = [ev for w in wrappers for ev in w.injected()]
        rep["resilience"] = gw.resilience_snapshot()
        # the decision timeline: every breaker/retry/hedge/brownout
        # transition, in order, on the simulated clock
        rep["timeline_resilience"] = tracer.events("resilience")
        return rep

    off = run(False)
    on = run(True)
    assert off["offered"] == on["offered"], (off["offered"],
                                             on["offered"])
    f_p99, a_p99 = off["ttft_s"]["p99"], on["ttft_s"]["p99"]
    # chaos acceptance pin, part 2: under the identical plan the
    # resilient gateway strictly beats resilience-off on tail latency
    # and finishes at least as much of the offered load
    assert a_p99 < f_p99, (a_p99, f_p99)
    assert on["outcomes"].get("finished", 0) >= \
        off["outcomes"].get("finished", 0), (on["outcomes"],
                                             off["outcomes"])

    def phase(rep):
        return {"offered": rep["offered"], "outcomes": rep["outcomes"],
                "shed_rate": round(rep["shed_rate"], 4),
                "ttft_s_p50": rep["ttft_s"]["p50"],
                "ttft_s_p99": rep["ttft_s"]["p99"],
                "faults_injected": len(rep["injected"])}

    counters = (on["resilience"] or {}).get("counters", {})
    return {"metric": "gpt_chaos_ttft_s_p99", "value": a_p99,
            "unit": "s", "direction": "lower",
            "mfu": None, "vs_baseline": None, "vs_a100_flops": None,
            "loss": 0.0, "backend": "tpu" if on_tpu else "cpu",
            "sim": {"workload": f"steady {RATE}/s", "horizon_s": HORIZON,
                    "dt_s": DT, "seed": SEED, "clock": "simulated",
                    "ttft_deadline_s": TTFT_DEADLINE,
                    "stall_threshold_s": STALL_THRESHOLD},
            "chaos": {
                "plan": plan.to_dict(),
                "resilience_off": phase(off),
                "resilience_on": phase(on),
                "p99_ttft_improvement": round(f_p99 / a_p99, 3),
                "counters": counters,
                "breakers": (on["resilience"] or {}).get("breakers"),
                "brownout": (on["resilience"] or {}).get("brownout"),
            },
            "decisions": on["timeline_resilience"]}


def bench_gpt_grad_comm(on_tpu):
    """Gradient-communication policy A/B on the sharded GPT trainer: one
    record comparing step time and bytes-on-wire across the grad_comm
    policies (fp32 / bf16 / int8_ef — distributed/grad_comm.py).  Byte
    figures are the policy layer's logical ring-all-reduce estimates from
    the grad-tree shapes (docs/DISTRIBUTED_COMM.md), reported per policy
    and as the int8_ef-vs-fp32 savings in the telemetry snapshot; step
    time measures the (de)quantization compute the policy adds to the
    compiled step on this backend."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.grad_comm import wire_bytes
    from paddle_tpu.models.gpt import GPTConfig, make_sharded_gpt_train_step
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.telemetry import TrainMonitor

    if on_tpu:
        cfg_kw = dict(vocab_size=50304, hidden_size=768, num_layers=12,
                      num_attention_heads=12, max_position_embeddings=1024,
                      compute_dtype="bfloat16", scan_unroll=12)
        B, L, iters = 16, 1024, 20
    else:
        cfg_kw = dict(vocab_size=512, hidden_size=128, num_layers=2,
                      num_attention_heads=4, max_position_embeddings=128,
                      compute_dtype="float32")
        B, L, iters = 2, 128, 3

    cfg = GPTConfig(**cfg_kw)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L)))
    y = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L)))

    policies = {}
    int8_comm = None
    dt_fp32 = loss_fp32 = None
    for pol in ("fp32", "bf16", "int8_ef"):
        paddle.seed(0)
        hcg = _fleet_hcg()
        mon = TrainMonitor()
        step, state = make_sharded_gpt_train_step(
            cfg, AdamW(3e-4, weight_decay=0.01), hcg, remat=False,
            grad_comm=pol)
        wb = wire_bytes(state["params"], pol)
        args = (state, np.float32(3e-4), jax.random.key(0), x, y)
        dt, loss, _ = _run_timed(step, args, iters, monitor=mon,
                                 examples_per_step=B, tokens_per_step=B * L)
        mon.record_comm(policy=pol, pre_bytes=wb["pre_bytes"],
                        post_bytes=wb["post_bytes"])
        tel = mon.summary()
        sw = tel["step_wall_s"] or {}
        if pol == "fp32":
            dt_fp32, loss_fp32 = dt, loss
        elif pol == "int8_ef":
            int8_comm = tel["comm"]
        policies[pol] = {
            "step_ms": round(dt / iters * 1e3, 3),
            "step_ms_p50": (None if sw.get("p50") is None
                            else round(sw["p50"] * 1e3, 3)),
            "tokens_per_sec": round(B * L * iters / dt, 1),
            "loss": round(loss, 4),
            "wire_bytes_fp32": wb["pre_bytes"],
            "wire_bytes": wb["post_bytes"],
            "wire_savings": round(wb["pre_bytes"] / wb["post_bytes"], 3),
        }

    base = policies["fp32"]
    flops = _transformer_train_flops(B, L, cfg.num_layers, cfg.hidden_size,
                                     cfg.intermediate_size, cfg.vocab_size)
    out = _result("gpt_grad_comm_tokens_per_sec", "tokens/s/chip", B * L,
                  iters, dt_fp32, flops, on_tpu, loss_fp32)
    out["policies"] = policies
    out["telemetry"] = {
        "comm": int8_comm,
        "int8_vs_fp32_bytes_savings": policies["int8_ef"]["wire_savings"],
        "int8_vs_fp32_step_ratio": (
            round(policies["int8_ef"]["step_ms"] / base["step_ms"], 3)
            if base["step_ms"] else None),
    }
    return out


def bench_gpt_weight_update_sharding(on_tpu):
    """Weight-update-sharding A/B on a plain data-parallel GPT
    (arXiv:2004.13336 via distributed/update_sharding.py): the replicated
    arm runs the ordinary GSPMD dp step (every replica updates the full
    optimizer state), the sharded arm updates each replica's 1/R shard
    between the reduce-scatter and the all-gather.  CPU-honest — the
    record attaches what this backend can measure truthfully: per-replica
    optimizer-state bytes (an addressable-shard census, backend-
    independent), update-step wall on THIS backend, the policy layer's
    logical wire-byte figures, and the loss-parity check that makes the
    A/B meaningful.  Acceptance pin (ISSUE 16): opt-state bytes per
    replica shrink >= 1.8x at R=2 with loss parity."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.distributed.grad_comm import wire_bytes
    from paddle_tpu.distributed.zero import per_device_state_bytes
    from paddle_tpu.models.gpt import (GPTConfig, GPTModel,
                                       make_gpt_train_step)
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.telemetry import TrainMonitor

    if on_tpu:
        cfg_kw = dict(vocab_size=50304, hidden_size=768, num_layers=12,
                      num_attention_heads=12, max_position_embeddings=1024,
                      compute_dtype="bfloat16", scan_unroll=12)
        B, L, iters = 16, 1024, 20
        R = jax.device_count()
    else:
        cfg_kw = dict(vocab_size=512, hidden_size=128, num_layers=2,
                      num_attention_heads=4, max_position_embeddings=128,
                      compute_dtype="float32")
        B, L, iters = 2, 128, 3
        R = 2

    cfg = GPTConfig(**cfg_kw)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L)))
    y = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L)))
    key = jax.random.key(0)

    def run_arm(update_sharding):
        paddle.seed(0)
        hcg = _fleet_hcg(dp_degree=R)
        mon = TrainMonitor()
        model = GPTModel(cfg)
        from paddle_tpu.telemetry_memory import MemoryLedger
        mem = MemoryLedger()
        with mem:   # active ledger: the builder registers state0 and the
            # instrument seam re-registers the donated state every step
            step, state = make_gpt_train_step(
                model, AdamW(3e-4, weight_decay=0.01), hcg, remat=False,
                monitor=mon, update_sharding=update_sharding)
            opt_bytes = per_device_state_bytes(state)
            wb = wire_bytes(state["params"], "fp32")
            # no AOT here: the update-sharded step owns its layout and
            # refuses .lower (models/gpt.py) — warm with one live dispatch,
            # then time the compiled program the same way on both arms
            state, loss = step(state, key, np.float32(3e-4), x, y)
            float(np.asarray(loss))
            t0 = time.perf_counter()
            for _ in range(iters):
                state, loss = step(state, key, np.float32(3e-4), x, y)
            final_loss = float(np.asarray(loss))
            dt = time.perf_counter() - t0
            # the MEASURED per-pool bytes (ISSUE 17): register the final
            # donated state, then one census over addressable shards —
            # replicated opt state on R devices counts R×, a 1/R flat
            # shard counts 1×, so per-replica = pool bytes / R
            mem.register_train_state(state, name="final_state")
            walk = mem.census()
        assert np.isfinite(final_loss), f"non-finite loss {final_loss}"
        measured = int(walk["pools"]["optimizer_state"]) // R
        return {"opt_bytes_per_replica": opt_bytes,
                "opt_bytes_per_replica_measured": measured,
                "step_ms": round(dt / iters * 1e3, 3),
                "tokens_per_sec": round(B * L * iters / dt, 1),
                "wire_bytes": wb["post_bytes"],
                "loss": final_loss}, dt, _memory_block(mem)

    replicated, _, mem_rep = run_arm(False)
    sharded, dt_sh, mem_sh = run_arm(True)

    # THE paper's claim, pinned: optimizer HBM per replica drops ~R x
    # while the schedule stays loss-identical (reduce-scatter + sharded
    # update + all-gather == all-reduce + replicated update)
    reduction = replicated["opt_bytes_per_replica"] / max(
        sharded["opt_bytes_per_replica"], 1)
    assert reduction >= 1.8, (
        f"opt-state reduction {reduction:.2f}x < 1.8x at R={R}")
    # the same claim, now MEASURED from the memory ledger's census rather
    # than the analytic shard arithmetic — the two must agree
    measured_reduction = replicated["opt_bytes_per_replica_measured"] / max(
        sharded["opt_bytes_per_replica_measured"], 1)
    assert measured_reduction >= 1.8, (
        f"measured opt-state reduction {measured_reduction:.2f}x < 1.8x "
        f"at R={R}")
    loss_delta = abs(sharded["loss"] - replicated["loss"])
    assert np.isclose(sharded["loss"], replicated["loss"],
                      rtol=1e-4, atol=1e-6), (
        f"loss parity broken: {replicated['loss']} vs {sharded['loss']}")

    flops = _transformer_train_flops(B, L, cfg.num_layers, cfg.hidden_size,
                                     cfg.intermediate_size, cfg.vocab_size)
    out = _result("gpt_weight_update_sharding_tokens_per_sec",
                  "tokens/s/chip", B * L, iters, dt_sh, flops, on_tpu,
                  sharded["loss"])
    for arm in (replicated, sharded):
        arm["loss"] = round(arm["loss"], 4)
    out["update_sharding"] = {
        "replicas": R,
        "replicated": replicated,
        "sharded": sharded,
        "opt_bytes_reduction": round(reduction, 3),
        "opt_bytes_reduction_measured": round(measured_reduction, 3),
        "loss_delta": round(loss_delta, 6),
    }
    # per-arm memory ledgers: pool live/peak bytes at steady state, so the
    # HBM claim above is a measured record, not a formula
    out["memory"] = {"replicated": mem_rep, "sharded": mem_sh}
    return out


def bench_gpt_train_resilience(on_tpu):
    """Supervisor on/off A/B under a seeded crash plan (ISSUE 20): the
    same tiny-GPT run is hit with an injected allocation failure, a torn
    checkpoint write, and a preemption request mid-run (the documented
    SIGTERM-equivalent boundary path — a real signal would chain to the
    harness's own handler on release).  Supervisor OFF dies at the first
    alloc_fail; supervisor ON restores from the last committed step,
    replays, takes a deadline-bounded emergency checkpoint at the
    preemption boundary, and a fresh supervisor resumes from it.
    Acceptance pin: the resumed trajectory equals the uninterrupted
    oracle BIT-EXACTLY (the two-phase commit + fold_in per-step RNG +
    iterator seek contract), and the torn step is counted-skipped, never
    loaded.  The record reports the recovery tax: recovery_time_s,
    steps_replayed, and the goodput fraction lost to replay."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.faults import Fault, FaultPlan, FaultInjectionError
    from paddle_tpu.models.gpt import GPTConfig, GPTModel, make_gpt_train_step
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.telemetry import Tracer
    from paddle_tpu.train_resilience import (CheckpointManager,
                                             PreemptionGuard,
                                             ResumableIterator,
                                             TrainSupervisor)

    if on_tpu:
        cfg_kw = dict(vocab_size=50304, hidden_size=768, num_layers=12,
                      num_attention_heads=12, max_position_embeddings=1024,
                      compute_dtype="bfloat16", scan_unroll=12)
        B, L = 16, 1024
    else:
        cfg_kw = dict(vocab_size=256, hidden_size=64, num_layers=1,
                      num_attention_heads=2, max_position_embeddings=64,
                      compute_dtype="float32")
        B, L = 2, 32
    NUM_STEPS, SAVE_EVERY, FAIL_AT, PREEMPT_AT = 24, 6, 9, 15
    cfg = GPTConfig(**cfg_kw)
    rng = np.random.RandomState(0)
    batches = [(jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L))),
                jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L))))
               for _ in range(8)]
    lr = np.float32(3e-4)

    def build():
        paddle.seed(0)
        hcg = _fleet_hcg(dp_degree=1)
        model = GPTModel(cfg)
        step, state = make_gpt_train_step(model, AdamW(3e-4), hcg,
                                          remat=False)
        return step, state

    import tempfile

    def supervised(root, fault_plan=None, preempt_at=None, num_steps=NUM_STEPS):
        step, state = build()
        guard = PreemptionGuard() if preempt_at is not None else None
        boundary = (lambda t, sup: sup.guard.request()
                    if t == preempt_at else None) if preempt_at else None
        sup = TrainSupervisor(
            step, state, CheckpointManager(root, tracer=Tracer(),
                                           fault_plan=fault_plan),
            base_key=jax.random.PRNGKey(0), lr=lr,
            data=ResumableIterator(batches), save_every=SAVE_EVERY,
            backoff_s=0.0, guard=guard, fault_plan=fault_plan,
            on_boundary=boundary)
        return sup, sup.run(num_steps)

    with tempfile.TemporaryDirectory() as td:
        # --- uninterrupted oracle
        t0 = time.perf_counter()
        _, oracle = supervised(os.path.join(td, "oracle"))
        oracle_wall = time.perf_counter() - t0
        assert oracle["completed"] and len(oracle["losses"]) == NUM_STEPS

        # --- supervisor OFF: the crash plan is fatal at the first fault
        plan_off = FaultPlan([Fault("alloc_fail", at_s=FAIL_AT, count=1)],
                             seed=7)
        step, state = build()
        data = ResumableIterator(batches)
        key = jax.random.PRNGKey(0)
        off_steps, off_died = 0, None
        try:
            for t in range(NUM_STEPS):
                for f in plan_off.faults:
                    if f.active(float(t)) and f.kind == "alloc_fail":
                        raise MemoryError(f"injected alloc_fail (step {t})")
                from paddle_tpu.jit.functional import fold_in_step_key
                state, _loss = step(state, fold_in_step_key(key, t), lr,
                                    *data.next_batch())
                off_steps = t + 1
        except (MemoryError, FaultInjectionError) as e:
            off_died = type(e).__name__

        # --- supervisor ON: same crash plan + torn write + preemption
        plan = FaultPlan([Fault("alloc_fail", at_s=FAIL_AT, count=1),
                          Fault("torn_write", at_s=1, count=1)], seed=7)
        root = os.path.join(td, "chaos")
        t0 = time.perf_counter()
        sup1, phase1 = supervised(root, fault_plan=plan,
                                  preempt_at=PREEMPT_AT)
        assert phase1["preempted"] and phase1["final_step"] == PREEMPT_AT
        # relaunch (the post-preemption restart): resume from the
        # emergency checkpoint and finish
        sup2, phase2 = supervised(root)
        chaos_wall = time.perf_counter() - t0
        assert phase2["completed"] and phase2["first_step"] == PREEMPT_AT

        # acceptance pin: bit-exact oracle equality across crash+preempt
        resumed = phase1["losses"] + phase2["losses"]
        assert resumed == oracle["losses"], "trajectory diverged"
        skips = dict(sup1.manager.skips)
        assert skips.get("uncommitted", 0) >= 1, skips  # torn step skipped
        snap1 = sup1.train_snapshot()

    replayed = phase1["steps_replayed"] + phase2["steps_replayed"]
    recovery_s = (phase1["recovery_time_s"] + phase2["recovery_time_s"])
    goodput = NUM_STEPS / (NUM_STEPS + replayed)
    out = _result("gpt_train_resilience_tokens_per_sec", "tokens/s",
                  B * L, NUM_STEPS, chaos_wall, None, on_tpu,
                  phase2["final_loss"])
    out["train_resilience"] = {
        "crash_plan": plan.to_dict(),
        "supervisor_off": {"completed": False, "died": off_died,
                           "steps_done": off_steps},
        "supervisor_on": {
            "completed": True,
            "restarts": phase1["restarts"] + phase2["restarts"],
            "steps_replayed": replayed,
            "recovery_time_s": round(recovery_s, 4),
            "corrupt_skips": skips,
            "saves_committed": snap1["saves_committed"],
            "saves_abandoned": snap1["saves_abandoned"],
            "final_loss_delta": abs(phase2["final_loss"] -
                                    oracle["final_loss"]),
            "goodput": round(goodput, 4),
            "goodput_delta_vs_oracle": round(1.0 - goodput, 4),
            "wall_overhead_x": round(chaos_wall / max(oracle_wall, 1e-9),
                                     3),
        },
    }
    return out


CONFIGS = {
    "gpt2s": bench_gpt2s,
    "gpt_long": bench_gpt_long,
    "bert_base": bench_bert_base,
    "ernie_moe": bench_ernie_moe,
    "resnet50": bench_resnet50,
    "mnist_lenet": bench_mnist_lenet,
    "gpt_decode": bench_gpt_decode,
    "gpt_serving": bench_gpt_serving,
    "gpt_serving_warmup": bench_gpt_serving_warmup,
    "gpt_kv_tier": bench_gpt_kv_tier,
    "gpt_gateway": bench_gpt_gateway,
    "gpt_autoscale": bench_gpt_autoscale,
    "gpt_chaos": bench_gpt_chaos,
    "gpt_grad_comm": bench_gpt_grad_comm,
    "gpt_weight_update_sharding": bench_gpt_weight_update_sharding,
    "gpt_train_resilience": bench_gpt_train_resilience,
}


def _child(names):
    import jax
    on_tpu = jax.default_backend() != "cpu"
    for name in names:
        print(json.dumps(CONFIGS[name](on_tpu)), flush=True)


def _run_group(cmd, env, timeout):
    """Run cmd in its own process group; on timeout SIGTERM the whole group
    (a plain subprocess timeout would orphan the grandchild holding the TPU
    claim, poisoning the backend for every later process)."""
    import signal as _signal

    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            cwd=os.path.dirname(os.path.abspath(__file__)),
                            start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
        return proc.returncode, stdout, stderr
    except subprocess.TimeoutExpired:
        stdout = stderr = ""
        try:
            os.killpg(proc.pid, _signal.SIGTERM)
            stdout, stderr = proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            try:  # SIGTERM-resistant (wedged in tunnel I/O): escalate
                os.killpg(proc.pid, _signal.SIGKILL)
            except (ProcessLookupError, OSError):
                pass
            try:
                stdout, stderr = proc.communicate(timeout=15)
            except subprocess.TimeoutExpired:
                pass
        except (ProcessLookupError, OSError):
            # group already gone: still reap the child and drain its pipes
            try:
                stdout, stderr = proc.communicate(timeout=15)
            except subprocess.TimeoutExpired:
                pass
        return "timeout", stdout or "", stderr or ""


_PROBE_SRC = """
import time, jax, jax.numpy as jnp, numpy as np
t0 = time.time(); d = len(jax.devices()); t1 = time.time()
x = jnp.ones((2048, 2048), jnp.bfloat16)
y = jax.jit(lambda a: a @ a)(x)
v = float(np.asarray(y[0, 0])); t2 = time.time()
k = getattr(jax.devices()[0], 'device_kind', '?').replace(' ', '_')
print(f'COMPUTE_HEALTHY backend={jax.default_backend()} devices={d} '
      f'kind={k} dial={t1-t0:.1f}s compute={t2-t1:.1f}s v={v}', flush=True)
"""


def _probe_health(healthy, rc, out):
    """The backend-health stamp every BENCH record header carries (ISSUE
    17): the probe's verdict plus the backend/device identity it saw, so
    a perf number is never read without knowing what produced it —
    ``tools/bench_diff.py`` refuses to call cross-backend pairs
    comparable and warns when A/B health stamps disagree."""
    detail = next((ln for ln in (out or "").splitlines()
                   if ln.startswith("COMPUTE_HEALTHY")), "")
    fields = dict(kv.split("=", 1) for kv in detail.split() if "=" in kv)
    devices = fields.get("devices")
    return {"compute_healthy": bool(healthy), "probe_rc": rc,
            "backend": fields.get("backend"),
            "devices": int(devices) if devices else None,
            "device_kind": fields.get("kind")}


def _health_log(line):
    """Append one timestamped line to the per-round health artifact so an
    infra-dead round is provable at a glance (VERDICT r3 weak #2)."""
    path = os.environ.get(
        "PADDLE_TPU_BENCH_HEALTH_LOG",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "HEALTH.log"))
    try:
        with open(path, "a") as f:
            f.write(time.strftime("%Y-%m-%d %H:%M:%S ", time.gmtime()) + line
                    + "\n")
    except OSError:
        pass


def _probe_backend(timeout=300.0):
    """Fast-fail when the device backend is down — and catch the half-up
    state too: jax.devices() can enumerate while compile/execute hangs
    (observed 2026-07-31 03:48, BENCH_NOTES.md), so health is a jitted
    2048^2 matmul ROUND-TRIP to host (the same check as the external
    compute sentinel loop documented in BENCH_NOTES.md) — never a bare
    devices() call.

    The deadline is HARD: a probe still running at ``timeout`` gets its
    whole process group SIGTERM'd (SIGKILL after a grace), and the real
    exit status is recorded.  The earlier leave-it-running policy
    ("rc=inflight ... [probe left running]", HEALTH.log 2026-08-01) traded
    one poisoned claim for an orphan that held the claim INDEFINITELY and
    queued every later probe behind the wedge — a bounded kill releases
    the claim at a known time and leaves a real rc in the log instead of
    a process leak.

    Returns (healthy, rc, detail); rc is the child's true returncode
    (negative = died on that signal number)."""
    import signal as _signal
    import tempfile
    outf = tempfile.NamedTemporaryFile(mode="w+", suffix=".probe", delete=False)
    timed_out = False
    proc = None            # Popen itself may raise; the finally must cope
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", _PROBE_SRC], stdout=outf, stderr=outf,
            start_new_session=True)
        deadline = time.time() + timeout
        while time.time() < deadline and proc.poll() is None:
            time.sleep(min(2.0, max(0.05, timeout / 10.0)))
        timed_out = proc.poll() is None
        if timed_out:
            # kill the whole group: the probe may have spawned a compile
            # helper holding the claim (same escalation as _run_group)
            for sig in (_signal.SIGTERM, _signal.SIGKILL):
                try:
                    os.killpg(proc.pid, sig)
                except (ProcessLookupError, OSError):
                    break
                try:
                    proc.wait(timeout=10)
                    break
                except subprocess.TimeoutExpired:
                    continue
            try:                      # reap so rc is real, never None
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        outf.flush()
        with open(outf.name) as f:
            out = f.read()
    finally:
        outf.close()
        if proc is not None and proc.poll() is not None:
            try:
                os.unlink(outf.name)
            except OSError:
                pass
    rc = proc.returncode          # negative = killed by that signal
    healthy = rc == 0 and not timed_out and "COMPUTE_HEALTHY" in out
    detail = next((ln for ln in out.splitlines()
                   if ln.startswith("COMPUTE_HEALTHY")), "")
    _health_log(f"probe rc={rc} {'ok ' + detail if healthy else 'FAIL'} "
                + ("" if healthy else out[-200:].replace("\n", " "))
                + (f" [probe killed at {timeout:.0f}s deadline]"
                   if timed_out else ""))
    return healthy, rc, out


def _parent(names, attempts, timeout):
    """Run configs in a child with retry; keep partial successes.

    The child prints one JSON line per config in order, so on a partial crash
    the first len(lines) configs succeeded — only the remainder is retried."""
    results = {}
    errors = []
    remaining = list(names)
    probe_tries = int(os.environ.get("PADDLE_TPU_BENCH_PROBE_ATTEMPTS", "3"))
    probe_backoff = float(os.environ.get("PADDLE_TPU_BENCH_PROBE_BACKOFF", "90"))
    probe_ok, probe_rc, probe_err = False, None, ""
    probe_errors = []
    for p in range(probe_tries):  # transient tunnel wedge ≠ dead round
        probe_ok, probe_rc, probe_err = _probe_backend(
            float(os.environ.get("PADDLE_TPU_BENCH_PROBE_TIMEOUT", "300")))
        if probe_ok:
            break
        probe_errors.append({"attempt": f"probe{p}", "rc": probe_rc,
                             "tail": "backend unhealthy (compute round-trip "
                                     "probe failed — see HEALTH.log): "
                                     + (probe_err or "")[-400:]})
        # a timed-out probe was killed with its whole group (hard deadline,
        # real rc) — the claim is released, so retrying after backoff is
        # safe even for the half-up wedge case
        if p < probe_tries - 1:
            time.sleep(probe_backoff)
    health = _probe_health(probe_ok, probe_rc, probe_err)
    if not probe_ok:
        # backend unhealthy ≠ benchmark failure: emit "skipped" records
        # carrying the probe tail, so the perf trajectory stays parseable
        # (an "error" here read as a code regression every infra-dead round)
        for name in names:
            print(json.dumps({
                "metric": f"{name}_train_throughput", "value": None,
                "unit": "skipped", "vs_baseline": None,
                "vs_a100_flops": None,
                "health": health,
                "skipped": {"reason": "backend unhealthy (compute "
                                      "round-trip probe failed — see "
                                      "HEALTH.log)",
                            "probe": probe_errors},
            }), flush=True)
        return 0
    for attempt in range(attempts):
        if not remaining:
            break
        env = dict(os.environ)
        env["_PADDLE_TPU_BENCH_CHILD"] = "1"
        rc, stdout, stderr = _run_group(
            [sys.executable, os.path.abspath(__file__), "--config",
             ",".join(remaining)], env, timeout)
        lines = [ln for ln in stdout.splitlines() if ln.strip().startswith("{")]
        for name, ln in zip(remaining, lines):
            try:
                results[name] = json.loads(ln)
            except ValueError:
                break
        remaining = [n for n in remaining if n not in results]
        if remaining:
            errors.append({"attempt": attempt, "rc": rc, "failed": remaining[0],
                           "tail": stderr[-600:]})
    for name in names:
        if name in results:
            rec = results[name]
            rec["health"] = health    # probe verdict stamped on success too
            print(json.dumps(rec), flush=True)
        else:
            print(json.dumps({
                "metric": f"{name}_train_throughput", "value": None,
                "unit": "error", "vs_baseline": None, "vs_a100_flops": None,
                "health": health,
                "error": {"attempts": len(errors), "detail": errors},
            }), flush=True)
    return 0  # structured error on stdout IS the artifact; don't die raw


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="gpt2s",
                    help="comma-separated config names, or 'all'")
    ap.add_argument("--attempts", type=int,
                    default=int(os.environ.get("PADDLE_TPU_BENCH_ATTEMPTS", "2")))
    ap.add_argument("--timeout", type=float,
                    default=float(os.environ.get("PADDLE_TPU_BENCH_TIMEOUT", "1200")))
    args = ap.parse_args()
    names = list(CONFIGS) if args.config == "all" else args.config.split(",")
    for n in names:
        if n not in CONFIGS:
            ap.error(f"unknown config {n!r}; choose from {list(CONFIGS)}")
    if os.environ.get("_PADDLE_TPU_BENCH_CHILD") == "1":
        # kernel A/B sweeps: export FLAGS_use_fused_ln=1 (the flag registry
        # env-seeds every FLAGS_* at import; the parent forwards the env)
        _child(names)
        return 0
    return _parent(names, args.attempts, args.timeout)


if __name__ == "__main__":
    sys.exit(main())
