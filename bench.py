"""Benchmark entry — prints ONE JSON line.

Measures GPT pretraining throughput (tokens/sec) on the available device
with the jit-compiled train step (bf16 compute, flash attention, fused
optimizer in-program).  vs_baseline compares against the A100 tokens/sec/chip
north-star proxy scaled to this model size (BASELINE.json publishes no
reference numbers — see BASELINE.md).
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models.gpt import GPTConfig, GPTModel, make_gpt_train_step
    from paddle_tpu.optimizer import AdamW

    paddle.seed(0)
    on_tpu = jax.default_backend() != "cpu"
    # GPT-2 small-ish config sized to fit one v5e chip comfortably in bf16
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_attention_heads=12, max_position_embeddings=1024,
                        compute_dtype="bfloat16")
        B, L, iters = 8, 1024, 30
    else:  # CI / smoke sizing
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_attention_heads=4, max_position_embeddings=128,
                        compute_dtype="float32")
        B, L, iters = 2, 128, 3

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()

    model = GPTModel(cfg)
    opt = AdamW(3e-4, weight_decay=0.01)
    step, state = make_gpt_train_step(model, opt, hcg, remat=False)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L)))
    y = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, L)))

    # warmup / compile.  NOTE: sync via host transfer (float(...)), not
    # block_until_ready — measured on this tunneled axon backend,
    # block_until_ready returned in ~40ms while the 20-step chain took ~3.4s
    # to actually finish (observed 2026-07-29), silently inflating throughput.
    state, loss = step(state, jax.random.key(0), np.float32(3e-4), x, y)
    float(loss)

    t0 = time.perf_counter()
    for i in range(iters):
        state, loss = step(state, jax.random.key(i + 1), np.float32(3e-4), x, y)
    final_loss = float(loss)  # forces completion of the whole chain
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)

    tokens_per_sec = B * L * iters / dt
    # A100 proxy for GPT-2-small-class training ≈ 150k tokens/s/chip (public
    # megatron-class numbers); vs_baseline = ours / proxy.  Note the local chip
    # is a v5e (~197 bf16 TFLOP/s peak vs A100's 312), so 1.0 here means beating
    # an A100 outright, not just matching per-peak-FLOP efficiency.
    baseline_proxy = 150_000.0 if on_tpu else tokens_per_sec
    print(json.dumps({
        "metric": "gpt2s_train_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(tokens_per_sec / baseline_proxy, 3),
    }))


if __name__ == "__main__":
    main()
